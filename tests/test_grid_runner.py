"""Unit tests for the canonical-grid runner's operational helpers.

The runner (sweeps/run_grid_canonical.py) is the round's unattended TPU
driver; its resume bookkeeping and opportunistic-bench logic must behave
exactly as documented because nobody watches it run (SURVEY.md §5
failure-detection analog)."""

import importlib.util
import json
import subprocess
import sys
import time
import types
from pathlib import Path

import pytest

_REPO_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture()
def runner(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "_grid_runner", _REPO_ROOT / "sweeps" / "run_grid_canonical.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "RESULTS_DIR", tmp_path)
    monkeypatch.setattr(mod, "OUT", tmp_path / "grid.jsonl")
    return mod


def test_done_cells_skips_truncated_rows(runner):
    rows = [
        {"cell": "a_slow", "truncated": False},
        {"cell": "b_slow", "truncated": True},   # resumed next run
        {"cell": "c_slow"},                       # legacy row, no flag
        {"cell": "b_slow", "truncated": False},  # later completion wins
    ]
    runner.OUT.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert runner.done_cells() == {"a_slow", "b_slow", "c_slow"}


def test_done_cells_empty_without_file(runner):
    assert runner.done_cells() == set()


def _fake_run(returncode=0, stdout="", stderr=""):
    def run(cmd, **kwargs):
        return types.SimpleNamespace(
            returncode=returncode, stdout=stdout, stderr=stderr
        )

    return run


def test_maybe_run_bench_consumes_marker_on_success(runner, monkeypatch):
    (runner.RESULTS_DIR / "BENCH_REQUEST").touch()
    monkeypatch.setattr(
        runner.subprocess, "run",
        _fake_run(stdout='{"metric": "x", "value": 1}\n'),
    )
    runner.maybe_run_bench(deadline=time.time() + 3600)
    assert not (runner.RESULTS_DIR / "BENCH_REQUEST").exists()
    out = (runner.RESULTS_DIR / "bench_opportunistic.jsonl").read_text()
    assert json.loads(out.strip())["value"] == 1


def test_maybe_run_bench_consumes_marker_on_failure(runner, monkeypatch):
    """A failing bench must not be retried forever on the chip's time —
    the marker is consumed either way (re-touch to request another)."""
    (runner.RESULTS_DIR / "BENCH_REQUEST").touch()
    monkeypatch.setattr(
        runner.subprocess, "run", _fake_run(returncode=1, stderr="boom")
    )
    runner.maybe_run_bench(deadline=time.time() + 3600)
    assert not (runner.RESULTS_DIR / "BENCH_REQUEST").exists()
    assert not (runner.RESULTS_DIR / "bench_opportunistic.jsonl").exists()


def test_maybe_run_bench_respects_deadline(runner, monkeypatch):
    """Too close to the deadline: no TPU time spent, marker kept for a
    future run with budget."""
    (runner.RESULTS_DIR / "BENCH_REQUEST").touch()

    def explode(*a, **k):  # pragma: no cover - must not be called
        raise AssertionError("bench launched past the deadline")

    monkeypatch.setattr(runner.subprocess, "run", explode)
    runner.maybe_run_bench(deadline=time.time() + 60)
    assert (runner.RESULTS_DIR / "BENCH_REQUEST").exists()


def test_maybe_run_bench_noop_without_marker(runner, monkeypatch):
    def explode(*a, **k):  # pragma: no cover
        raise AssertionError("bench launched without a request")

    monkeypatch.setattr(runner.subprocess, "run", explode)
    runner.maybe_run_bench(deadline=time.time() + 3600)


def test_version_for_matches_log_layout(runner):
    assert runner.version_for("mse", "small", "slow") == "mse_small_lr0.0001_slow"


def test_ensure_checkpoint_noop_when_confirmed(runner, monkeypatch, tmp_path):
    ckpt = tmp_path / "best"
    ckpt.mkdir()
    (tmp_path / "best.ENSURED").touch()

    def explode(*a, **k):  # pragma: no cover - must not be called
        raise AssertionError("retrained despite a confirmed checkpoint")

    monkeypatch.setattr(runner.subprocess, "run", explode)
    assert runner.ensure_checkpoint("c", [], ckpt, time.time() + 3600)


def _fake_supervisor(runner, monkeypatch, verdicts, on_launch=None):
    """Replace the runner's RunSupervisor with a recording fake.

    Cells now train under masters_thesis_tpu.resilience (the supervisor
    owns retry/rollback; tests/test_resilience.py pins those policies), so
    these tests fake the supervisor seam rather than subprocess.run.
    ``verdicts`` is consumed one per launch (last one repeats);
    ``on_launch(cmd)`` simulates the child's side effects.
    """
    from masters_thesis_tpu.resilience.supervisor import (
        AttemptOutcome,
        Classification,
        SupervisorResult,
    )

    calls = []

    class FakeSupervisor:
        def __init__(self, cmd, run_dir, cfg=None, **kwargs):
            calls.append({"cmd": cmd, "cfg": cfg, "kwargs": kwargs})
            self.run_dir = Path(run_dir)

        def run(self):
            if on_launch is not None:
                on_launch(calls[-1]["cmd"])
            verdict = verdicts[min(len(calls) - 1, len(verdicts) - 1)]
            kind = "success" if verdict == "completed" else "transient"
            return SupervisorResult(
                ok=verdict == "completed",
                verdict=verdict,
                attempts=[AttemptOutcome(
                    attempt=1, rc=0 if verdict == "completed" else 1,
                    wall_s=0.1,
                    classification=Classification(kind=kind, reason=verdict),
                )],
            )

    monkeypatch.setattr(runner, "RunSupervisor", FakeSupervisor)
    return calls


def test_ensure_checkpoint_retrains_missing(runner, monkeypatch, tmp_path):
    """An environment reset wipes logs/ but not the results JSONL: the
    recorded pretrain cell must be retrained (not skipped) so the warmup
    block can warm-start from it. Completion writes the marker, so a second
    call is a no-op."""
    ckpt = tmp_path / "best"

    def publish_ckpt(cmd):
        assert "train.py" in cmd[1]
        ckpt.mkdir()

    monkeypatch.setattr(runner, "wait_for_tpu", lambda deadline: True)
    calls = _fake_supervisor(
        runner, monkeypatch, ["completed"], on_launch=publish_ckpt
    )
    assert runner.ensure_checkpoint("c", [], ckpt, time.time() + 3600)
    assert (tmp_path / "best.ENSURED").exists()
    assert runner.ensure_checkpoint("c", [], ckpt, time.time() + 3600)
    assert len(calls) == 1


def test_ensure_checkpoint_reports_failure(runner, monkeypatch, tmp_path):
    ckpt = tmp_path / "best"
    monkeypatch.setattr(runner, "wait_for_tpu", lambda deadline: True)
    _fake_supervisor(runner, monkeypatch, ["retries_exhausted"])
    assert not runner.ensure_checkpoint("c", [], ckpt, time.time() + 3600)


def test_ensure_checkpoint_rejects_partial_on_timeout(
    runner, monkeypatch, tmp_path
):
    """A budget-truncated retrain leaves a PARTIAL checkpoint at the
    target path; ensure_checkpoint must not bless it (the warmup
    comparison would warm-start from under-trained weights), and a later
    call must resume training rather than fast-path on existence."""
    ckpt = tmp_path / "best"

    def partial_ckpt(cmd):
        ckpt.mkdir(exist_ok=True)  # val-epoch checkpoint landed mid-train

    monkeypatch.setattr(runner, "wait_for_tpu", lambda deadline: True)
    calls = _fake_supervisor(
        runner, monkeypatch, ["budget_exhausted"], on_launch=partial_ckpt
    )
    assert not runner.ensure_checkpoint("c", [], ckpt, time.time() + 3600)
    assert not (tmp_path / "best.ENSURED").exists()
    # Second call: checkpoint exists but is unconfirmed -> trains again.
    assert not runner.ensure_checkpoint("c", [], ckpt, time.time() + 3600)
    assert len(calls) == 2


def test_train_with_retry_retries_transient_backend_failure(
    runner, monkeypatch
):
    """Transient retry now lives in the supervisor: the runner must hand
    it a config that retries with resume enabled, and map a completed
    verdict to (completed, not truncated)."""
    monkeypatch.setattr(runner, "wait_for_tpu", lambda deadline: True)
    calls = _fake_supervisor(runner, monkeypatch, ["completed"])
    completed, truncated = runner.train_with_retry(
        "c", [], budget=3600, deadline=time.time() + 3600
    )
    assert completed and not truncated
    assert len(calls) == 1
    cfg = calls[0]["cfg"]
    assert cfg.max_retries >= 1
    assert cfg.retry_budget_s <= 3600
    assert cfg.attempt_timeout_s <= 3600
    assert "trainer.resume=auto" in calls[0]["cmd"]


def test_ab_sweep_survives_child_timeout(monkeypatch, capsys):
    """One starved/wedged child must cost its POINT, not the sweep: the
    orchestrator skips it and still reports the points that ran
    (regression: an uncaught TimeoutExpired killed the whole A/B run)."""
    spec = importlib.util.spec_from_file_location(
        "_fused_bench", _REPO_ROOT / "sweeps" / "bench_fused_pair.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    calls = []

    def fake_child(cmd, **kwargs):
        mode = cmd[cmd.index("--child") + 1]
        calls.append(mode)
        if mode == "perlayer":
            raise subprocess.TimeoutExpired(cmd, 900)
        return types.SimpleNamespace(
            returncode=0,
            stdout=json.dumps(
                {"mode": mode, "model": "small", "steps_per_sec": 100.0}
            ),
            stderr="",
        )

    # The up-front backend probe (added r5) spawns its own subprocess via
    # the SHARED subprocess module — stub it out so the fake below only
    # ever sees --child invocations.
    import masters_thesis_tpu.utils as mt_utils

    monkeypatch.setattr(
        mt_utils, "probe_tpu_backend",
        lambda **kw: types.SimpleNamespace(ok=True, attempts=1, detail=""),
    )
    monkeypatch.setattr(mod.subprocess, "run", fake_child)
    monkeypatch.setattr(mod.sys, "argv", ["bench_fused_pair.py", "small"])
    mod.main()
    out = capsys.readouterr().out
    assert "TIMEOUT" in out and "skipping" in out
    assert calls == list(mod.MODES)  # every point attempted
    assert '"mode": "pair"' in out  # surviving points still reported


def test_ab_sweep_skips_whole_run_when_probe_fails(monkeypatch, capsys):
    """A wedged relay must cost a bounded probe, not 12 x per-child cap:
    the sweep bails before spawning any child (r5: twelve 900s SIGKILLs
    against a wedged lease, each kill itself a wedge trigger)."""
    spec = importlib.util.spec_from_file_location(
        "_fused_bench", _REPO_ROOT / "sweeps" / "bench_fused_pair.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    import masters_thesis_tpu.utils as mt_utils

    monkeypatch.setattr(
        mt_utils, "probe_tpu_backend",
        lambda **kw: types.SimpleNamespace(
            ok=False, attempts=5, detail="probe timed out (wedged lease)"
        ),
    )

    def no_children(*a, **k):  # pragma: no cover - the bail must prevent this
        raise AssertionError("probe failed but a child was spawned")

    monkeypatch.setattr(mod.subprocess, "run", no_children)
    monkeypatch.setattr(mod.sys, "argv", ["bench_fused_pair.py"])
    mod.main()
    out = capsys.readouterr().out
    assert "skipping the A/B sweep" in out


def test_renderer_warmup_table(monkeypatch, tmp_path, capsys):
    """The scratch-vs-warmup table must render per-objective verdicts and
    tolerate half-complete pairs (warmup cell still pending)."""
    spec = importlib.util.spec_from_file_location(
        "_renderer", _REPO_ROOT / "sweeps" / "render_grid_results.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    def row(cell, mix_model, mix_ols):
        return {
            "cell": cell, "epoch": 31, "train_wall_s": 60.0,
            "model": {"delta_mse": 1e-2, "delta_nll": 1.0,
                      "delta_mix": mix_model},
            "ols": {"delta_mse": 2e-2, "delta_nll": 2.0,
                    "delta_mix": mix_ols},
        }

    out = tmp_path / "grid.jsonl"
    out.write_text("".join(json.dumps(r) + "\n" for r in [
        row("outliers_mse_large_scratch", 2139.0, 2299.0),
        row("outliers_mse_large_warmup", 2050.0, 2299.0),
        row("outliers_nll_large_scratch", 1000.0, 1100.0),  # warmup pending
    ]))
    monkeypatch.setattr(mod, "OUT", out)
    # Hermetic from the repo's real midscale insurance results.
    monkeypatch.setattr(mod, "MIDSCALE", tmp_path / "absent.jsonl")
    mod.main()
    text = capsys.readouterr().out
    assert "| mse | 2139.000 | 2050.000 | 2299.000 | yes |" in text
    assert "| nll | 1000.000 | None | 1100.000 | ? |" in text


def test_renderer_midscale_section(monkeypatch, tmp_path, capsys):
    """Midscale insurance rows render in their own clearly-labeled table,
    never mixed into the canonical one."""
    spec = importlib.util.spec_from_file_location(
        "_renderer_mid", _REPO_ROOT / "sweeps" / "render_grid_results.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    def row(cell, mix_model, mix_ols):
        return {
            "cell": cell, "epoch": 31, "train_wall_s": 60.0,
            "model": {"delta_mse": 1e-2, "delta_nll": 1.0,
                      "delta_mix": mix_model},
            "ols": {"delta_mse": 2e-2, "delta_nll": 2.0,
                    "delta_mix": mix_ols},
        }

    canonical = tmp_path / "grid.jsonl"
    canonical.write_text(json.dumps(row("mse_small_slow", 1.0, 2.0)) + "\n")
    mid = tmp_path / "mid.jsonl"
    mid.write_text("".join(json.dumps(r) + "\n" for r in [
        row("mid_outliers_mse_small_scratch", 300.0, 400.0),
        row("mid_outliers_mse_small_warmup", 250.0, 400.0),
    ]))
    monkeypatch.setattr(mod, "OUT", canonical)
    monkeypatch.setattr(mod, "MIDSCALE", mid)
    mod.main()
    text = capsys.readouterr().out
    assert "1/20th scale" in text
    assert "| mse | 300.000 | 250.000 | 400.000 | yes |" in text
    # No canonical warmup section: no scratch/warmup cells in the grid.
    assert "fine-tune dataset: outliers DGP" not in text


def test_train_with_retry_truncates_on_timeout(runner, monkeypatch):
    _fake_supervisor(runner, monkeypatch, ["budget_exhausted"])
    completed, truncated = runner.train_with_retry(
        "c", [], budget=3600, deadline=time.time() + 3600
    )
    assert not completed and truncated
