"""LSTM encoder tests, including the numerical cross-check against
torch.nn.LSTM (the cuDNN-parity risk called out in SURVEY.md §7)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from masters_thesis_tpu.models import LstmEncoder


def _init(model, batch=3, time=12, features=5, seed=0):
    x = jnp.asarray(
        np.random.default_rng(seed).normal(size=(batch, time, features)),
        jnp.float32,
    )
    params = model.init(jax.random.PRNGKey(seed), x)
    return params, x


def test_output_shapes():
    model = LstmEncoder(hidden_size=16, num_layers=2, dropout=0.2)
    params, x = _init(model)
    alpha, beta = model.apply(params, x)
    assert alpha.shape == (3, 1)
    assert beta.shape == (3, 1)
    assert alpha.dtype == jnp.float32


def test_param_init_is_symmetric_uniform():
    model = LstmEncoder(hidden_size=32, num_layers=1, dropout=0.0)
    params, _ = _init(model)
    k = 1.0 / math.sqrt(32)
    w = np.asarray(params["params"]["w_ih_l0"])
    assert w.min() >= -k and w.max() <= k
    assert w.min() < -0.8 * k and w.max() > 0.8 * k  # actually spans the range
    assert abs(w.mean()) < 0.1 * k


def test_dropout_train_vs_eval():
    model = LstmEncoder(hidden_size=8, num_layers=3, dropout=0.5)
    params, x = _init(model)
    eval_out = model.apply(params, x, deterministic=True)
    eval_out2 = model.apply(params, x, deterministic=True)
    np.testing.assert_array_equal(np.asarray(eval_out[0]), np.asarray(eval_out2[0]))

    train_out = model.apply(
        params, x, deterministic=False, rngs={"dropout": jax.random.PRNGKey(1)}
    )
    train_out2 = model.apply(
        params, x, deterministic=False, rngs={"dropout": jax.random.PRNGKey(2)}
    )
    assert not np.allclose(np.asarray(train_out[0]), np.asarray(train_out2[0]))
    assert not np.allclose(np.asarray(train_out[0]), np.asarray(eval_out[0]))


def test_jit_matches_eager():
    model = LstmEncoder(hidden_size=8, num_layers=2, dropout=0.2)
    params, x = _init(model)
    eager = model.apply(params, x)
    jitted = jax.jit(lambda p, v: model.apply(p, v))(params, x)
    np.testing.assert_allclose(
        np.asarray(eager[0]), np.asarray(jitted[0]), rtol=1e-5, atol=1e-6
    )


def test_bf16_compute_close_to_f32():
    model32 = LstmEncoder(hidden_size=16, num_layers=2, dropout=0.0)
    params, x = _init(model32)
    model16 = LstmEncoder(
        hidden_size=16, num_layers=2, dropout=0.0, compute_dtype=jnp.bfloat16
    )
    a32, b32 = model32.apply(params, x)
    a16, b16 = model16.apply(params, x)
    assert a16.dtype == jnp.float32  # heads cast back
    np.testing.assert_allclose(np.asarray(a32), np.asarray(a16), atol=0.05)


@pytest.mark.parametrize("num_layers,features", [(1, 3), (2, 3), (3, 5)])
def test_matches_torch_lstm(num_layers, features):
    """Load identical weights into torch.nn.LSTM + Linear heads and into
    LstmEncoder; outputs must agree to float32 tolerance."""
    torch = pytest.importorskip("torch")
    torch.manual_seed(0)
    hidden = 16
    batch, time = 4, 20

    t_lstm = torch.nn.LSTM(
        input_size=features,
        hidden_size=hidden,
        num_layers=num_layers,
        dropout=0.0,
        batch_first=True,
    )
    t_alpha = torch.nn.Linear(hidden, 1)
    t_beta = torch.nn.Linear(hidden, 1)

    x_np = np.random.default_rng(1).normal(size=(batch, time, features)).astype(
        np.float32
    )
    with torch.no_grad():
        out, _ = t_lstm(torch.from_numpy(x_np))
        final = out[:, -1, :]
        ref_alpha = t_alpha(final).numpy()
        ref_beta = t_beta(final).numpy()

    model = LstmEncoder(hidden_size=hidden, num_layers=num_layers, dropout=0.0)
    params = {"params": {}}
    for layer in range(num_layers):
        params["params"][f"w_ih_l{layer}"] = jnp.asarray(
            getattr(t_lstm, f"weight_ih_l{layer}").detach().numpy()
        )
        params["params"][f"w_hh_l{layer}"] = jnp.asarray(
            getattr(t_lstm, f"weight_hh_l{layer}").detach().numpy()
        )
        params["params"][f"b_ih_l{layer}"] = jnp.asarray(
            getattr(t_lstm, f"bias_ih_l{layer}").detach().numpy()
        )
        params["params"][f"b_hh_l{layer}"] = jnp.asarray(
            getattr(t_lstm, f"bias_hh_l{layer}").detach().numpy()
        )
    params["params"]["alpha_head"] = {
        "kernel": jnp.asarray(t_alpha.weight.detach().numpy().T),
        "bias": jnp.asarray(t_alpha.bias.detach().numpy()),
    }
    params["params"]["beta_head"] = {
        "kernel": jnp.asarray(t_beta.weight.detach().numpy().T),
        "bias": jnp.asarray(t_beta.bias.detach().numpy()),
    }

    alpha, beta = model.apply(params, jnp.asarray(x_np))
    np.testing.assert_allclose(np.asarray(alpha), ref_alpha, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(beta), ref_beta, rtol=1e-4, atol=1e-5)


def test_gradients_flow_through_all_layers():
    model = LstmEncoder(hidden_size=8, num_layers=2, dropout=0.0)
    params, x = _init(model)

    def loss_fn(p):
        a, b = model.apply(p, x)
        return jnp.sum(a**2) + jnp.sum(b**2)

    grads = jax.grad(loss_fn)(params)
    flat = jax.tree_util.tree_leaves(grads)
    assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)
    assert any(np.any(np.asarray(g) != 0) for g in flat)
    # Recurrent weights of both layers receive gradient.
    for layer in range(2):
        g = np.asarray(grads["params"][f"w_hh_l{layer}"])
        assert np.any(g != 0)


@pytest.mark.slow
def test_remat_gradients_match_plain():
    """jax.checkpoint over the recurrence must not change gradients — only
    the backward's memory/recompute schedule (the long-lookback knob)."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(3, 40, 3)).astype(np.float32))

    plain = LstmEncoder(hidden_size=8, num_layers=2, dropout=0.0)
    remat = LstmEncoder(hidden_size=8, num_layers=2, dropout=0.0, remat=True)
    params = plain.init(jax.random.key(0), x)

    def loss(module, p):
        alpha, beta = module.apply(p, x)
        return jnp.sum(alpha**2) + jnp.sum(beta**2)

    g_plain = jax.grad(lambda p: loss(plain, p))(params)
    g_remat = jax.grad(lambda p: loss(remat, p))(params)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_plain), jax.tree_util.tree_leaves(g_remat)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)
