"""Pass-4 SPMD divergence lint (DV7xx) + the collective-schedule audit.

Each DV rule gets a seeded fixture proving it fires, a near-identical
clean twin proving precision, and a suppressed variant. The runtime
half gets unit coverage of the hash chain and the cross-rank audit,
plus the acceptance scenario: a simulated 2-rank fleet where an
injected rank-divergent branch is caught statically AND the postmortem
exits 2 naming the divergent rank, the fork entry, and both chains.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from masters_thesis_tpu.analysis.spmd import lint_spmd
from masters_thesis_tpu.telemetry.schedule import (
    CollectiveSchedule,
    audit_schedules,
    read_rank_schedules,
)

_REPO_ROOT = Path(__file__).resolve().parent.parent
_PKG_ROOT = _REPO_ROOT / "masters_thesis_tpu"
_WORKER = _REPO_ROOT / "tests" / "_spmd_worker.py"


def _lint(tmp_path: Path, source: str, name: str = "fix.py", **kwargs):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return lint_spmd([tmp_path], **kwargs)


def _rules(findings):
    return {f.rule for f in findings}


# ------------------------------------------------------------------- DV701


def test_dv701_rank_branch_guards_barrier(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import jax
        from masters_thesis_tpu.parallel.mesh import fleet_barrier


        def publish(tag):
            if jax.process_index() == 0:
                fleet_barrier(f"publish.{tag}")
        """,
    )
    assert _rules(findings) == {"DV701"}
    assert "only one side" in findings[0].message


def test_dv701_env_early_exit_skips_schedule(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import os
        from masters_thesis_tpu.parallel.mesh import fleet_barrier


        def run():
            if os.environ.get("MTT_SKIP"):
                return
            fleet_barrier("epoch")
        """,
    )
    assert _rules(findings) == {"DV701"}
    assert "early exit" in findings[0].message


def test_dv701_tainted_loop_bound(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import jax
        from jax import lax


        def reduce_all(shards):
            for shard in range(len(jax.local_devices())):
                lax.psum(shard, "data")
        """,
    )
    # The tainted loop var is also the psum operand, so DV703 rides along.
    assert "DV701" in _rules(findings)
    assert "trip counts" in [
        f for f in findings if f.rule == "DV701"
    ][0].message


def test_dv701_clean_uniform_guard(tmp_path):
    # process_count() is uniform across ranks — the single-process guard
    # inside fleet_barrier itself must never fire.
    findings = _lint(
        tmp_path,
        """
        import jax
        from jax.experimental import multihost_utils


        def fleet_barrier(name):
            if jax.process_count() <= 1:
                return
            multihost_utils.sync_global_devices(name)
        """,
    )
    assert findings == []


def test_dv701_clean_barrier_outside_gate(tmp_path):
    # Rank-gated work with the barrier OUTSIDE the branch: every rank
    # reaches the same schedule — no divergence.
    findings = _lint(
        tmp_path,
        """
        import jax
        from masters_thesis_tpu.parallel.mesh import fleet_barrier


        def publish(tag, payload):
            if jax.process_index() == 0:
                print("publishing", tag)
            fleet_barrier(f"publish.{tag}")
        """,
    )
    assert findings == []


def test_dv701_suppressed_with_reason(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import jax
        from masters_thesis_tpu.parallel.mesh import fleet_barrier


        def publish(tag):
            if jax.process_index() == 0:  # mtt: disable=DV701 -- single-rank debug tool, never run on a fleet
                fleet_barrier(f"publish.{tag}")
        """,
    )
    assert findings == []


# ------------------------------------------------------------------- DV702


def test_dv702_branches_issue_different_schedules(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import jax
        from jax import lax
        from masters_thesis_tpu.parallel.mesh import fleet_barrier


        def step(x, rank):
            if rank == 0:
                fleet_barrier("sync")
                lax.psum(x, "data")
            else:
                lax.psum(x, "data")
                fleet_barrier("sync")
        """,
    )
    assert "DV702" in _rules(findings)
    assert "schedules differ" in [
        f for f in findings if f.rule == "DV702"
    ][0].message


def test_dv702_clean_same_schedule_both_branches(tmp_path):
    # Divergent control flow is fine when both sides issue the SAME
    # schedule (e.g. different logging around the same collective).
    findings = _lint(
        tmp_path,
        """
        from jax import lax


        def step(x, rank):
            if rank == 0:
                print("lead")
                lax.psum(x, "data")
            else:
                lax.psum(x, "data")
        """,
    )
    assert [f for f in findings if f.rule == "DV702"] == []


# ------------------------------------------------------------------- DV703


def test_dv703_rank_flows_into_collective_operand(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import jax
        from jax import lax


        def bad(x):
            offset = jax.process_index() * 10
            return lax.psum(x + offset, "data")
        """,
    )
    assert "DV703" in _rules(findings)
    assert "collective operand" in [
        f for f in findings if f.rule == "DV703"
    ][0].message


def test_dv703_host_len_flows_into_traced_shape(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp


        def bad():
            n_local = len(jax.local_devices())
            return jnp.zeros(n_local)
        """,
    )
    assert "DV703" in _rules(findings)
    assert "traced array shape" in [
        f for f in findings if f.rule == "DV703"
    ][0].message


def test_dv703_clean_uniform_shape(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp


        def ok(batch_size):
            n = jax.device_count()
            return jnp.zeros(batch_size // n)
        """,
    )
    assert findings == []


# ------------------------------------------------------------------- DV704


def test_dv704_wall_clock_on_publish_path(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import json
        import time


        def save_checkpoint(path, payload):
            payload["ts"] = time.time()
            path.write_text(json.dumps(payload))
        """,
    )
    assert "DV704" in _rules(findings)
    assert "wall clock" in [
        f for f in findings if f.rule == "DV704"
    ][0].message


def test_dv704_unseeded_rng_on_resume_path(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import random


        def restore_checkpoint(candidates):
            return random.choice(candidates)
        """,
    )
    assert "DV704" in _rules(findings)
    assert "unseeded RNG" in [
        f for f in findings if f.rule == "DV704"
    ][0].message


def test_dv704_unsorted_dir_iteration_transitively_reachable(tmp_path):
    # The nondeterminism sits in a helper the entry point calls — the
    # class-aware callgraph must carry reachability through it.
    findings = _lint(
        tmp_path,
        """
        def _scan(ckpt_dir):
            out = []
            for p in ckpt_dir.iterdir():
                out.append(p)
            return out


        def restore_checkpoint(ckpt_dir):
            return _scan(ckpt_dir)[-1]
        """,
    )
    assert "DV704" in _rules(findings)
    assert "iteration order" in [
        f for f in findings if f.rule == "DV704"
    ][0].message


def test_dv704_clean_seeded_and_sorted(tmp_path):
    # Seeded RNG and sorted() iteration are deterministic; the same ops
    # OUTSIDE the checkpoint path never fire at all.
    findings = _lint(
        tmp_path,
        """
        import random


        def save_checkpoint(ckpt_dir, seed):
            rng = random.Random(seed)
            order = sorted(ckpt_dir.iterdir())
            for p in order:
                pass
            return rng.random()


        def unrelated_tool(d):
            for p in d.iterdir():
                pass
        """,
    )
    assert findings == []


# ------------------------------------------------------------------- DV705


def test_dv705_unfenced_rank0_side_effect(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import jax


        def promote(staging, final):
            if jax.process_index() == 0:
                staging.replace(final)
        """,
    )
    assert _rules(findings) == {"DV705"}
    assert "no named barrier" in findings[0].message


def test_dv705_transitive_side_effect_through_helper(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import jax
        import shutil


        def _promote(staging, final):
            shutil.move(staging, final)


        def publish(staging, final):
            if jax.process_index() == 0:
                _promote(staging, final)
        """,
    )
    assert _rules(findings) == {"DV705"}


def test_dv705_clean_when_fenced(tmp_path):
    # The repo's save_checkpoint/_run_recovery shape: rank-0 mutation +
    # a named barrier later in the same function.
    findings = _lint(
        tmp_path,
        """
        import jax
        from masters_thesis_tpu.parallel.mesh import fleet_barrier


        def promote(staging, final, tag):
            if jax.process_index() == 0:
                staging.replace(final)
            fleet_barrier(f"publish.{tag}")
        """,
    )
    assert findings == []


def test_dv705_regression_unfenced_recovery_shape(tmp_path):
    # Regression pin for the _run_recovery fix: the PRE-fix shape (rank-0
    # renames, peers poll, no barrier) must keep firing DV705 so the
    # barrier can never be dropped silently.
    findings = _lint(
        tmp_path,
        """
        import jax


        def _recover_staged(ckpt_dir, tag):
            (ckpt_dir / f"{tag}.new").replace(ckpt_dir / tag)


        def _run_recovery(ckpt_dir, tag):
            if jax.process_index() == 0:
                _recover_staged(ckpt_dir, tag)
        """,
    )
    assert _rules(findings) == {"DV705"}


# ------------------------------------------- interprocedural taint plumbing


def test_return_taint_crosses_functions(tmp_path):
    # process_identity()-style helper: the rank taint must survive the
    # tuple-return / tuple-unpack round trip into the guard.
    findings = _lint(
        tmp_path,
        """
        import os
        from masters_thesis_tpu.parallel.mesh import fleet_barrier


        def identity():
            proc = int(os.environ.get("JAX_PROCESS_INDEX", "0"))
            nproc = int(os.environ.get("JAX_PROCESS_COUNT", "1"))
            return proc, nproc


        def run(tag):
            proc, nproc = identity()
            if proc == 0:
                fleet_barrier(f"lead.{tag}")
        """,
    )
    assert "DV701" in _rules(findings)


def test_rank_param_name_is_a_source(tmp_path):
    findings = _lint(
        tmp_path,
        """
        from masters_thesis_tpu.parallel.mesh import fleet_barrier


        def run(rank):
            if rank == 0:
                fleet_barrier("lead")
        """,
    )
    assert "DV701" in _rules(findings)


# --------------------------------------------------- suppression surfacing


def test_include_suppressed_marks_instead_of_dropping(tmp_path):
    src = """
        import jax
        from masters_thesis_tpu.parallel.mesh import fleet_barrier


        def publish(tag):
            if jax.process_index() == 0:  # mtt: disable=DV701 -- intentional single-rank path
                fleet_barrier(f"publish.{tag}")
    """
    assert _lint(tmp_path, src) == []
    kept = _lint(tmp_path, src, include_suppressed=True)
    assert len(kept) == 1
    assert kept[0].rule == "DV701"
    assert kept[0].suppressed is True
    assert "[suppressed]" in kept[0].format()


def test_cli_json_carries_suppression_state(tmp_path):
    out = subprocess.run(
        [
            sys.executable, "-m", "masters_thesis_tpu.analysis",
            "--spmd", "--json",
        ],
        cwd=_REPO_ROOT,
        capture_output=True,
        text=True,
    )
    # The repo lints clean modulo reasoned suppressions, so --json exits
    # 0 while still listing every suppressed finding for CI's inventory.
    assert out.returncode == 0, out.stdout + out.stderr
    findings = json.loads(out.stdout)
    assert all(set(f) >= {"rule", "message", "path", "line", "suppressed"}
               for f in findings)
    assert all(f["suppressed"] for f in findings)


# ------------------------------------------------------- acceptance: repo


def test_repo_lints_clean_under_spmd_pass():
    findings = lint_spmd(
        [
            _PKG_ROOT / "train",
            _PKG_ROOT / "parallel",
            _PKG_ROOT / "resilience",
            _PKG_ROOT / "telemetry",
        ],
        package_root=_PKG_ROOT,
    )
    assert findings == [], "\n".join(f.format() for f in findings)


def test_run_recovery_is_barrier_fenced():
    # The dogfooded DV705 fix: recovery must end at a named barrier so a
    # non-zero rank can't read the pre-recovery tree.
    src = (_PKG_ROOT / "train" / "checkpoint.py").read_text()
    assert 'fleet_barrier(f"checkpoint.recover.{tag}")' in src


# ------------------------------------------------------ hash chain (unit)


def test_chain_is_deterministic_and_order_sensitive():
    a, b, c = (CollectiveSchedule() for _ in range(3))
    for s in (a, b):
        s.record("pmean", name="grads", step=0)
        s.record("barrier", name="epoch.0", step=0)
    c.record("barrier", name="epoch.0", step=0)
    c.record("pmean", name="grads", step=0)
    assert a.snapshot()["chain"] == b.snapshot()["chain"]
    assert a.snapshot()["chain"] != c.snapshot()["chain"]
    assert a.snapshot()["n"] == 2


def test_chain_tail_is_bounded():
    s = CollectiveSchedule(keep=4)
    for i in range(10):
        s.record("barrier", name=f"b{i}", step=i)
    snap = s.snapshot()
    assert snap["n"] == 10
    assert [e["step"] for e in snap["tail"]] == [6, 7, 8, 9]


def test_audit_match_and_insufficient():
    a, b = CollectiveSchedule(), CollectiveSchedule()
    for s in (a, b):
        s.record("barrier", name="x")
    ok = audit_schedules({"p0": a.snapshot(), "p1": b.snapshot()})
    assert ok["ok"] and ok["verdict"] == "match"
    one = audit_schedules({"p0": a.snapshot(), "p1": None})
    assert one["ok"] and one["verdict"] == "insufficient"


def test_audit_names_divergent_rank_and_step():
    lead, lag = CollectiveSchedule(), CollectiveSchedule()
    for step in range(4):
        lead.record("pmean", name="grads", step=step)
        lead.record("barrier", name=f"epoch.{step}", step=step)
        lag.record("pmean", name="grads", step=step)
        if step != 2:  # the divergent rank skips step 2's barrier
            lag.record("barrier", name=f"epoch.{step}", step=step)
    audit = audit_schedules(
        {"p0": lead.snapshot(), "p1": lag.snapshot()}
    )
    assert not audit["ok"]
    assert audit["verdict"] == "diverged"
    assert audit["divergent_rank"] == "p1"
    assert audit["index"] == 5  # first fork: p0's step-2 barrier slot
    assert "epoch.2" in audit["detail"]
    assert set(audit["schedules"]) == {"p0", "p1"}


def test_audit_lagging_is_not_divergence():
    lead, lag = CollectiveSchedule(), CollectiveSchedule()
    for step in range(4):
        lead.record("barrier", name=f"epoch.{step}", step=step)
        if step < 2:  # same prefix, then silence (wedged/killed rank)
            lag.record("barrier", name=f"epoch.{step}", step=step)
    audit = audit_schedules(
        {"p0": lead.snapshot(), "p1": lag.snapshot()}
    )
    assert audit["ok"] and audit["verdict"] == "lagging"
    assert audit["laggard"] == "p1"
    assert "epoch.2" in audit["detail"]


def test_read_rank_schedules_prefers_freshest_record(tmp_path):
    s = CollectiveSchedule()
    s.record("barrier", name="a")
    stale = s.snapshot()
    s.record("barrier", name="b")
    fresh = s.snapshot()
    p0 = tmp_path / "g0" / "p0"
    p0.mkdir(parents=True)
    (p0 / "heartbeat.json").write_text(
        json.dumps({"collective_schedule": stale})
    )
    (p0 / "crashdump.json").write_text(
        json.dumps({"collective_schedule": fresh})
    )
    snaps = read_rank_schedules(tmp_path / "g0")
    assert snaps["p0"]["n"] == 2
    assert snaps["p0"]["chain"] == fresh["chain"]


def test_fleetsup_generation_audit_reads_rank_dirs(tmp_path):
    # The supervisor-side audit consumes exactly what read_rank_schedules
    # returns for a generation directory — fabricate a diverged g0.
    lead, lag = CollectiveSchedule(), CollectiveSchedule()
    for step in range(3):
        lead.record("barrier", name=f"epoch.{step}", step=step)
        if step != 1:
            lag.record("barrier", name=f"epoch.{step}", step=step)
    for rank, sched in (("p0", lead), ("p1", lag)):
        d = tmp_path / "g0" / rank
        d.mkdir(parents=True)
        (d / "heartbeat.json").write_text(
            json.dumps({"collective_schedule": sched.snapshot()})
        )
    audit = audit_schedules(read_rank_schedules(tmp_path / "g0"))
    assert not audit["ok"]
    assert audit["divergent_rank"] == "p1"
    assert audit["step"] is not None


# ------------------------------------- acceptance: 2-rank fleet scenario


def _run_fleet(root: Path, scenario: str) -> None:
    env = {**os.environ, "PYTHONPATH": str(_REPO_ROOT)}
    procs = [
        subprocess.Popen(
            [sys.executable, str(_WORKER), str(root), str(r), "2",
             scenario],
            cwd=_REPO_ROOT,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for r in (0, 1)
    ]
    for p in procs:
        out, err = p.communicate(timeout=60)
        assert p.returncode == 0, err
        assert out.strip().endswith("done"), out


def _postmortem(root: Path) -> tuple[int, str]:
    out = subprocess.run(
        [sys.executable, "-m", "masters_thesis_tpu.telemetry",
         "postmortem", str(root)],
        cwd=_REPO_ROOT,
        capture_output=True,
        text=True,
    )
    return out.returncode, out.stdout + out.stderr


def test_injected_divergence_caught_statically():
    # Static half of the acceptance criterion: the worker's injected
    # rank-divergent branch is a DV701 at the exact line.
    findings = lint_spmd([_WORKER])
    dv701 = [f for f in findings if f.rule == "DV701"]
    assert dv701, "\n".join(f.format() for f in findings)
    src_lines = _WORKER.read_text().splitlines()
    flagged = src_lines[dv701[0].line - 1]
    assert "scenario == \"divergent\"" in flagged


@pytest.mark.slow
def test_divergent_fleet_postmortem_exits_2_naming_rank_and_step(tmp_path):
    _run_fleet(tmp_path, "divergent")
    code, text = _postmortem(tmp_path)
    assert code == 2, text
    assert "DIVERGED" in text
    assert "rank p1" in text          # the divergent rank, by name
    assert "entry 5" in text          # the fork index
    assert "barrier name=epoch.2" in text  # the skipped step's barrier
    # Both schedule hash chains, named with their lengths.
    assert "(8 entries)" in text and "(7 entries)" in text


@pytest.mark.slow
def test_healthy_fleet_chains_match_and_exit_0(tmp_path):
    _run_fleet(tmp_path, "healthy")
    code, text = _postmortem(tmp_path)
    assert code == 0, text
    assert "match" in text
    assert "DIVERGED" not in text
