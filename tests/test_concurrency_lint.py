"""Pass-3 static analysis: concurrency lint (CL5xx) + event contracts
(EC6xx) + the unified suppression parser (SP001).

Each rule gets a seeded fixture module proving it fires, a suppression
proving it can be silenced (with a reason), and the final test pins the
acceptance criterion: the repo itself lints clean under both passes.
"""

from __future__ import annotations

import json
import textwrap
from pathlib import Path

import pytest

from masters_thesis_tpu.analysis.concurrency import lint_concurrency
from masters_thesis_tpu.analysis.contracts import build_schema, lint_contracts
from masters_thesis_tpu.analysis.findings import (
    parse_suppressions,
    suppression_findings,
)


def _lint(tmp_path: Path, source: str, name: str = "fix.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return lint_concurrency([tmp_path])


def _rules(findings):
    return {f.rule for f in findings}


# ------------------------------------------------------------------- CL501


LOCK_ORDER_CYCLE = """
    import threading


    class Pair:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def ab(self):
            with self._a:
                with self._b:
                    pass

        def ba(self):
            with self._b:
                with self._a:
                    pass
"""


def test_cl501_lock_order_inversion(tmp_path):
    findings = _lint(tmp_path, LOCK_ORDER_CYCLE)
    cl501 = [f for f in findings if f.rule == "CL501"]
    assert len(cl501) == 2  # one per edge of the cycle
    assert "opposite" in cl501[0].message or "reverse" in cl501[0].message


def test_cl501_interprocedural_cycle(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import threading


        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def _inner_b(self):
                with self._b:
                    pass

            def ab(self):
                with self._a:
                    self._inner_b()

            def ba(self):
                with self._b:
                    with self._a:
                        pass
        """,
    )
    assert "CL501" in _rules(findings)


def test_cl501_no_cycle_no_finding(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import threading


        class Ordered:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def also_ab(self):
                with self._a:
                    with self._b:
                        pass
        """,
    )
    assert "CL501" not in _rules(findings)


def test_cl501_rlock_reentry_ok(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import threading


        class Reent:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner_op()

            def inner_op(self):
                with self._lock:
                    pass
        """,
    )
    assert "CL501" not in _rules(findings)


# ------------------------------------------------------------------- CL502


UNGUARDED_COUNTER = """
    import threading
    import time


    class Stats:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            while True:
                self.count += 1
                time.sleep(0.01)

        def snapshot(self):
            with self._lock:
                return self.count
"""


def test_cl502_unguarded_rmw_counter(tmp_path):
    findings = _lint(tmp_path, UNGUARDED_COUNTER)
    cl502 = [f for f in findings if f.rule == "CL502"]
    assert cl502, findings
    assert "count" in cl502[0].message
    assert "read-modify-write" in cl502[0].message


def test_cl502_guarded_counter_clean(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import threading
        import time


        class Stats:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                while True:
                    with self._lock:
                        self.count += 1
                    time.sleep(0.01)

            def snapshot(self):
                with self._lock:
                    return self.count
        """,
    )
    assert "CL502" not in _rules(findings)


def test_cl502_single_threaded_class_not_flagged(tmp_path):
    # No thread ever runs this class's methods: a bare += is fine.
    findings = _lint(
        tmp_path,
        """
        class Tally:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
        """,
    )
    assert "CL502" not in _rules(findings)


def test_cl502_event_attr_exempt(tmp_path):
    # threading.Event IS the synchronization; reading it unlocked is the
    # point, not a race.
    findings = _lint(
        tmp_path,
        """
        import threading


        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.stop_event = threading.Event()
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                while not self.stop_event.is_set():
                    pass

            def stop(self):
                with self._lock:
                    self.stop_event.set()
        """,
    )
    assert "CL502" not in _rules(findings)


# ------------------------------------------------------------------- CL503


SLEEP_UNDER_LOCK = """
    import threading
    import time


    class Slow:
        def __init__(self):
            self._lock = threading.Lock()

        def slow(self):
            with self._lock:
                time.sleep(1.0)
"""


def test_cl503_blocking_sleep_under_lock(tmp_path):
    findings = _lint(tmp_path, SLEEP_UNDER_LOCK)
    cl503 = [f for f in findings if f.rule == "CL503"]
    assert cl503
    assert "time.sleep" in cl503[0].message


def test_cl503_interprocedural(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import threading
        import time


        class Slow:
            def __init__(self):
                self._lock = threading.Lock()

            def _io(self):
                time.sleep(0.5)

            def slow(self):
                with self._lock:
                    self._io()
        """,
    )
    assert "CL503" in _rules(findings)


def test_cl503_condition_wait_exempt(tmp_path):
    # cond.wait() releases the condition it waits on — that's its job.
    findings = _lint(
        tmp_path,
        """
        import threading


        class Q:
            def __init__(self):
                self._cond = threading.Condition()

            def pop(self):
                with self._cond:
                    self._cond.wait(0.1)
        """,
    )
    assert "CL503" not in _rules(findings)


# ------------------------------------------------------------------- CL504


def test_cl504_blocking_acquire_in_handler(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import signal
        import threading


        class Rec:
            def __init__(self):
                self._lock = threading.Lock()
                signal.signal(signal.SIGTERM, self._on_signal)

            def _on_signal(self, signum, frame):
                self.dump()

            def dump(self):
                with self._lock:
                    return 1
        """,
    )
    cl504 = [f for f in findings if f.rule == "CL504"]
    assert cl504
    assert "_lock" in cl504[0].message


def test_cl504_bounded_acquire_ok(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import signal
        import threading


        class Rec:
            def __init__(self):
                self._lock = threading.Lock()
                signal.signal(signal.SIGTERM, self._on_signal)

            def _on_signal(self, signum, frame):
                self.dump()

            def dump(self):
                if not self._lock.acquire(timeout=0.25):
                    return None
                try:
                    return 1
                finally:
                    self._lock.release()
        """,
    )
    assert "CL504" not in _rules(findings)


# ------------------------------------------------------------------- CL505


def test_cl505_nondaemon_never_joined(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import threading


        def fire_and_forget(work):
            t = threading.Thread(target=work)
            t.start()
        """,
    )
    cl505 = [f for f in findings if f.rule == "CL505"]
    assert cl505
    assert "never joined" in cl505[0].message


def test_cl505_init_spawn_without_stop_path(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import threading


        class Daemonish:
            def __init__(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                pass
        """,
    )
    assert "CL505" in _rules(findings)


def test_cl505_joined_thread_clean(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import threading


        class Clean:
            def __init__(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                pass

            def close(self):
                self._t.join(timeout=1.0)
        """,
    )
    assert "CL505" not in _rules(findings)


# ------------------------------------------- suppressions (unified parser)


def test_suppression_silences_with_reason(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import threading
        import time


        class Slow:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1.0)  # mtt: disable=CL503 -- test fixture
        """,
    )
    assert "CL503" not in _rules(findings)
    assert "SP001" not in _rules(findings)


def test_bare_suppression_is_itself_a_finding(tmp_path):
    findings = _lint(
        tmp_path,
        """
        import threading
        import time


        class Slow:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1.0)  # mtt: disable=CL503
        """,
    )
    # The reason-less suppression still works, but the gate reports it.
    assert "CL503" not in _rules(findings)
    assert "SP001" in _rules(findings)


def test_unified_parser_spellings():
    src = (
        "a = 1  # mtt: disable=CL502 -- why\n"
        "b = 2  # tracelint: disable=TL101\n"
        "c = 3  # noqa: TL103\n"
        "d = 4  # noqa\n"
    )
    sups = {s.line: s for s in parse_suppressions(src)}
    assert sups[1].spelling == "mtt"
    assert sups[1].rules == frozenset({"CL502"})
    assert sups[1].reason == "why"
    assert sups[2].spelling == "tracelint" and sups[2].reason is None
    assert sups[3].spelling == "noqa"
    assert 4 not in sups  # bare noqa never swallows findings
    sp = suppression_findings(src, "x.py")
    assert [f.line for f in sp] == [2]  # only the reason-less tracelint


# --------------------------------------------------------------- contracts


def _contracts(tmp_path: Path, source: str, schema_path=None):
    (tmp_path / "fix.py").write_text(textwrap.dedent(source))
    return lint_contracts([tmp_path], schema_path=schema_path)


READER_OF_MISSING_FIELD = """
    def emit_all(sink):
        sink.emit("epoch", epoch=1, wall_s=2.5)


    def read_all(events):
        by_kind = {}
        for ev in events:
            by_kind.setdefault(ev.get("kind", "?"), []).append(ev)
        return [e.get("gpu_util") for e in by_kind.get("epoch", [])]
"""


def test_ec601_consumed_never_emitted(tmp_path):
    findings = _contracts(tmp_path, READER_OF_MISSING_FIELD)
    ec601 = [f for f in findings if f.rule == "EC601"]
    assert ec601
    assert "gpu_util" in ec601[0].message and "epoch" in ec601[0].message


def test_ec601_satisfied_contract_clean(tmp_path):
    findings = _contracts(
        tmp_path,
        """
        def emit_all(sink):
            sink.emit("epoch", epoch=1, wall_s=2.5)


        def read_all(events):
            by_kind = {}
            for ev in events:
                by_kind.setdefault(ev.get("kind", "?"), []).append(ev)
            return [e.get("wall_s") for e in by_kind.get("epoch", [])]
        """,
    )
    assert "EC601" not in _rules(findings)


def test_ec601_dynamic_kind_exempt(tmp_path):
    findings = _contracts(
        tmp_path,
        """
        def emit_all(sink, payload):
            sink.emit("metrics", **payload)


        def read_all(by_kind):
            return [e.get("whatever") for e in by_kind.get("metrics", [])]
        """,
    )
    assert "EC601" not in _rules(findings)


def test_ec601_kind_guard_binding(tmp_path):
    # `if ev.get("kind") == ...` binds the var without a by_kind map.
    findings = _contracts(
        tmp_path,
        """
        def emit_all(sink):
            sink.emit("epoch", epoch=1)


        def read_all(events):
            for ev in events:
                if ev.get("kind") == "epoch":
                    print(ev.get("missing_one"))
        """,
    )
    assert any(
        f.rule == "EC601" and "missing_one" in f.message for f in findings
    )


def test_ec602_emitter_type_conflict(tmp_path):
    findings = _contracts(
        tmp_path,
        """
        def emit_a(sink):
            sink.emit("epoch", wall_s=2.5)


        def emit_b(sink):
            sink.emit("epoch", wall_s="fast")
        """,
    )
    ec602 = [f for f in findings if f.rule == "EC602"]
    assert ec602
    assert "wall_s" in ec602[0].message


def test_ec602_reader_numeric_cast_of_str(tmp_path):
    findings = _contracts(
        tmp_path,
        """
        def emit_all(sink):
            sink.emit("epoch", label="third")


        def read_all(by_kind):
            return [float(e.get("label")) for e in by_kind.get("epoch", [])]
        """,
    )
    assert any(
        f.rule == "EC602" and "casts" in f.message for f in findings
    )


def test_ec603_drift_and_regeneration(tmp_path):
    (tmp_path / "fix.py").write_text(
        textwrap.dedent(
            """
            def emit_all(sink):
                sink.emit("epoch", epoch=1, wall_s=2.5)
            """
        )
    )
    lock = tmp_path / "schema.json"
    # Missing lockfile -> EC603.
    findings = lint_contracts([tmp_path], schema_path=lock)
    assert any(
        f.rule == "EC603" and "missing" in f.message for f in findings
    )
    # Fresh lockfile -> clean.
    lock.write_text(json.dumps(build_schema([tmp_path])))
    assert not lint_contracts([tmp_path], schema_path=lock)
    # Emitter gains a field -> drift.
    (tmp_path / "fix.py").write_text(
        textwrap.dedent(
            """
            def emit_all(sink):
                sink.emit("epoch", epoch=1, wall_s=2.5, new_field=0)
            """
        )
    )
    findings = lint_contracts([tmp_path], schema_path=lock)
    assert any(
        f.rule == "EC603" and "new_field" in f.message for f in findings
    )


def test_ec_suppression(tmp_path):
    findings = _contracts(
        tmp_path,
        """
        def emit_all(sink):
            sink.emit("epoch", epoch=1)


        def read_all(by_kind):
            return [e.get("gone") for e in by_kind.get("epoch", [])]  # mtt: disable=EC601 -- test fixture
        """,
    )
    assert "EC601" not in _rules(findings)


# ----------------------------------------------------- repo acceptance gate


@pytest.mark.slow
def test_repo_lints_clean_concurrency():
    import masters_thesis_tpu

    root = Path(masters_thesis_tpu.__file__).parent
    findings = lint_concurrency([root], package_root=root)
    assert findings == [], "\n".join(f.format() for f in findings)


@pytest.mark.slow
def test_repo_lints_clean_contracts():
    import masters_thesis_tpu

    root = Path(masters_thesis_tpu.__file__).parent
    findings = lint_contracts(
        [root],
        package_root=root,
        schema_path=root / "analysis" / "event_schema.json",
    )
    assert findings == [], "\n".join(f.format() for f in findings)


def test_schema_lockfile_checked_in_and_fresh():
    import masters_thesis_tpu

    root = Path(masters_thesis_tpu.__file__).parent
    lock = root / "analysis" / "event_schema.json"
    assert lock.exists(), "run python -m masters_thesis_tpu.analysis --emit-schema"
    current = build_schema([root], package_root=root)
    assert json.loads(lock.read_text()) == current, (
        "event_schema.json is stale — regenerate with --emit-schema"
    )
