"""TensorBoard logger round-trip + figure library behavior.

The reference gets these behaviors from Lightning's TensorBoardLogger and
eyeballs the figures (reference: train.py:143-148, src/plots.py); here both
are owned code, so both get tests: scalars written must be readable back out
of the event files, and each figure kind must carry its statistical
annotations.
"""

import numpy as np
import pytest
from tensorboard.backend.event_processing.event_accumulator import (
    EventAccumulator,
)

from masters_thesis_tpu.train.logging import TensorBoardLogger
from masters_thesis_tpu.viz import (
    estimation_plots,
    estimation_scatter,
    hist_plot,
    scatter_plot,
)


def _read_scalars(log_dir):
    acc = EventAccumulator(str(log_dir))
    acc.Reload()
    return {
        tag: [(e.step, e.value) for e in acc.Scalars(tag)]
        for tag in acc.Tags()["scalars"]
    }


class TestTensorBoardLogger:
    def test_scalar_roundtrip(self, tmp_path):
        tb = TensorBoardLogger(tmp_path, "name/sub", "v0")
        tb.log_scalars({"loss/total/train": 1.5, "lr": 0.1}, step=0)
        tb.log_scalar("loss/total/train", 1.25, step=1)
        tb.close()
        assert tb.log_dir == tmp_path / "name" / "sub" / "v0"
        scalars = _read_scalars(tb.log_dir)
        assert [v for _, v in scalars["loss/total/train"]] == [1.5, 1.25]
        assert scalars["lr"][0] == (0, pytest.approx(0.1))

    def test_hparams_and_figures_write_events(self, tmp_path):
        tb = TensorBoardLogger(tmp_path, "n", "v")
        tb.log_hparams(
            {"model.hidden_size": 64, "loss.name": "mse", "none": None},
            {"test/mae": 0.5},
        )
        fig = scatter_plot(np.arange(10.0), np.arange(10.0), title="t")
        tb.log_figure("scatter/x", fig)
        tb.close()
        acc = EventAccumulator(str(tb.log_dir))
        acc.Reload()
        assert acc.Tags()["images"]  # the figure landed
        event_files = list(tb.log_dir.rglob("events.out.tfevents.*"))
        assert len(event_files) >= 2  # main + hparams sub-run


class TestFigures:
    def test_scatter_has_identity_and_corr(self):
        a = np.linspace(0, 1, 50)
        fig = scatter_plot(a, a, title="Alphas")
        ax = fig.axes[0]
        assert "corr=1.0000" in ax.get_title()
        assert len(ax.lines) == 1  # identity line

    def test_hist_bins_scale_with_samples(self):
        data = np.random.default_rng(0).normal(size=1000)
        fig = hist_plot(data, data + 1, title="resid")
        ax = fig.axes[0]
        # bins = 1% of n + 1 (reference: src/plots.py:30-54).
        assert len(ax.patches) == 2 * (int(1000 * 0.01) + 1)
        assert len(ax.get_legend().get_texts()) >= 2

    def test_estimation_scatter_two_panels(self):
        rng = np.random.default_rng(1)
        t = rng.normal(size=(40, 3))
        fig = estimation_scatter(t + 0.1 * rng.normal(size=t.shape), t, t)
        assert len(fig.axes) == 2

    def test_estimation_plots_caps_at_nine_stocks(self, tmp_path):
        tb = TensorBoardLogger(tmp_path, "n", "v")
        n_win, n_stocks = 20, 12
        rng = np.random.default_rng(2)
        ests = rng.normal(size=(n_win, n_stocks))
        estimation_plots(tb, ests, ests, ests, est_kind="beta")
        tb.close()
        # size_guidance images=0 -> keep all (the default caps at 4)
        acc = EventAccumulator(str(tb.log_dir), size_guidance={"images": 0})
        acc.Reload()
        # one figure per stock, first <=9 stocks only (src/plots.py:56-76)
        imgs = acc.Images("estimation/examples_beta")
        assert len(imgs) == 9
