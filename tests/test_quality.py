"""Model-quality plane (ISSUE 18): drift sketches, shadow-OLS monitoring,
quality-gated hot-swap.

Covers all three lifecycle stages plus the chaos contract the issue
names in BOTH directions: the ``shift`` fault fires the input-drift and
shadow-disagreement alerts within a bounded number of sampled windows,
while an IID twin run stays silent; the swap quality gate rejects a
diverged fine-tune with a named ``quality_*`` reason while an honest
candidate (and a fingerprint-less legacy checkpoint) still commits.
"""

import json
import time
from pathlib import Path

import numpy as np
import pytest

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.resilience.faults import FaultPlan, FaultSpec
from masters_thesis_tpu.telemetry import TelemetryRun, read_events
from masters_thesis_tpu.telemetry import quality as q
from masters_thesis_tpu.telemetry.__main__ import main as cli_main
from masters_thesis_tpu.telemetry.report import summarize_events
from masters_thesis_tpu.telemetry.slo import SLOEngine, default_quality_rules

# Window shape shared by every engine/checkpoint in this file (matches
# test_serve.py so the AOT predict programs stay tiny).
K, T, F = 4, 8, 3


@pytest.fixture(autouse=True)
def _no_leaked_faults(monkeypatch):
    """Every test starts and ends with injection off, whatever it does."""
    monkeypatch.delenv(faults.FAULT_PLAN_ENV, raising=False)
    monkeypatch.delenv(faults.ATTEMPT_ENV, raising=False)
    yield
    faults.clear_plan()


def _windows(n, n_stocks=6, lookback=32, n_features=3,
             scale=1.0, offset=0.0, seed=11):
    """Seeded window batch + its honest shadow-OLS outputs."""
    g = np.random.default_rng(seed)
    xs = g.standard_normal((n, n_stocks, lookback, n_features))
    xs = (xs * scale + offset).astype(np.float32)
    a, b = q.shadow_ols(xs)
    return xs, a, b


# ------------------------------------------------------------- sketch math


class TestSketchMath:
    def test_p2_quantiles_track_exact(self):
        data = np.random.default_rng(0).standard_normal(5000)
        sk = q.StreamSketch()
        sk.update(data)
        got = np.asarray(sk.summary()["quantiles"])
        want = np.quantile(data, np.asarray(q.QUANTILE_GRID))
        assert np.all(np.abs(got - want) < 0.08)

    def test_from_values_is_exact_and_matches_streaming_moments(self):
        data = np.random.default_rng(1).standard_normal(3000)
        exact = q.StreamSketch.from_values(data).summary()
        assert exact["quantiles"] == [
            float(np.quantile(data, p)) for p in q.QUANTILE_GRID
        ]
        streamed = q.StreamSketch()
        streamed.update(data)
        s = streamed.summary()
        assert s["count"] == exact["count"] == data.size
        assert s["mean"] == pytest.approx(exact["mean"], abs=1e-9)
        assert s["var"] == pytest.approx(exact["var"], rel=1e-6)
        assert (s["min"], s["max"]) == (exact["min"], exact["max"])

    def test_nonfinite_values_are_dropped(self):
        sk = q.StreamSketch()
        sk.update([1.0, np.nan, 2.0, np.inf, -np.inf, 3.0])
        assert sk.count == 3
        assert sk.summary()["max"] == 3.0

    def test_psi_ks_quiet_on_iid_loud_under_shift(self):
        base = np.random.default_rng(2).standard_normal(20_000)
        ref = q.StreamSketch.from_values(base[:10_000]).summary()
        iid = q.StreamSketch.from_values(base[10_000:]).summary()
        shifted = q.StreamSketch.from_values(
            base[10_000:] * 1.5 + 0.75
        ).summary()
        assert q.psi(ref, iid) < 0.02 and q.ks(ref, iid) < 0.03
        assert q.psi(ref, shifted) > 0.3 and q.ks(ref, shifted) > 0.2
        # Empty sketches never alarm.
        empty = q.StreamSketch().summary()
        assert q.psi(ref, empty) == 0.0 and q.ks(empty, ref) == 0.0

    def test_sketch_json_round_trip_is_bit_stable(self):
        ref = q.StreamSketch.from_values(
            np.random.default_rng(3).standard_normal(500)
        ).summary()
        js = q.sketch_to_json(ref)
        assert q.sketch_to_json(q.sketch_from_json(js)) == js

    def test_shadow_ols_matches_per_window_polyfit(self):
        x = np.random.default_rng(4).standard_normal((3, 5, 24, 3))
        sa, sb = q.shadow_ols(x)
        assert sa.shape == sb.shape == (3, 5)
        for n in range(3):
            for k in range(5):
                b1, b0 = np.polyfit(x[n, 0, :, 1], x[n, k, :, 0], 1)
                assert sa[n, k] == pytest.approx(b0, abs=1e-8)
                assert sb[n, k] == pytest.approx(b1, abs=1e-8)
        # A model that IS the OLS baseline has zero shadow disagreement.
        assert q.shadow_error(x, sa, sb) == pytest.approx(0.0, abs=1e-9)

    def test_golden_windows_deterministic(self):
        a = q.golden_windows(4, K, T, F, seed=0)
        b = q.golden_windows(4, K, T, F, seed=0)
        c = q.golden_windows(4, K, T, F, seed=1)
        assert a.shape == (4, K, T, F) and a.dtype == np.float32
        assert np.array_equal(a, b)
        assert not np.array_equal(a, c)


# ------------------------------------------------------------ fingerprints


class TestFingerprint:
    def test_build_sections_and_json_round_trip(self):
        fx, fa, fb = _windows(40)
        gx = q.golden_windows(8, 6, 32, 3, seed=0)
        ga, gb = q.shadow_ols(gx)
        fp = q.build_fingerprint(
            fx, fa, fb, golden=(gx, ga, gb), golden_seed=0, max_windows=32
        )
        assert fp["windows"] == 32  # capped by max_windows
        assert fp["window_shape"] == [6, 32, 3]
        assert set(fp["features"]) == {"0", "1", "2"}
        assert fp["shadow"]["err_mean"] == pytest.approx(0.0, abs=1e-9)
        assert fp["golden"]["shape"] == [8, 6, 32, 3]
        assert fp["golden"]["seed"] == 0
        js = q.fingerprint_to_json(fp)
        assert q.fingerprint_to_json(json.loads(js)) == js

    def test_read_fingerprint_missing_or_torn_is_none(self, tmp_path):
        assert q.read_fingerprint(tmp_path / "nope") is None
        tree = tmp_path / "best"
        tree.mkdir()
        (tree / q.FINGERPRINT_FILENAME).write_text("{torn")
        assert q.read_fingerprint(tree) is None
        (tree / q.FINGERPRINT_FILENAME).write_text('{"version": 1}')
        assert q.read_fingerprint(tree) == {"version": 1}


# ------------------------------------------------------- the `shift` fault


class TestShiftFault:
    def test_shift_is_a_declared_kind(self):
        spec = FaultSpec(point="serve.admit", kind="shift", attempt=None)
        assert spec.kind == "shift"
        with pytest.raises(ValueError):
            FaultSpec(point="serve.admit", kind="wobble")

    def test_shift_params_seeded_and_bounded(self):
        faults.install_plan(FaultPlan(faults=(), seed=5))
        try:
            s1 = faults.shift_params()
            s2 = faults.shift_params()
        finally:
            faults.clear_plan()
        faults.install_plan(FaultPlan(faults=(), seed=6))
        try:
            s3 = faults.shift_params()
            s4 = faults.shift_params(extra=1)
        finally:
            faults.clear_plan()
        assert s1 == s2  # same plan seed -> same regime
        assert s1 != s3  # different plan seed -> different regime
        assert s3 != s4  # per-epoch `extra` decorrelates
        for scale, off in (s1, s3, s4):
            assert 1.25 <= scale <= 1.75
            assert 0.25 <= off <= 0.75

    def test_admit_shift_transforms_the_request_deterministically(self):
        from masters_thesis_tpu.serve.queue import (
            MicroBatchQueue,
            ServeRequest,
        )

        faults.install_plan(
            FaultPlan(
                faults=(
                    FaultSpec(point="serve.admit", kind="shift", attempt=None),
                ),
                seed=5,
            )
        )
        try:
            scale, offset = faults.shift_params()
            queue = MicroBatchQueue(max_batch=2)
            x = np.ones((K, T, F), np.float32)
            req = ServeRequest(
                rid=1, x=x.copy(), deadline_ts=time.monotonic() + 10.0
            )
            pending = queue.submit(req)
        finally:
            faults.clear_plan()
        assert not pending.done  # shifted, not shed: still serveable
        assert pending.request.x.dtype == np.float32
        np.testing.assert_allclose(
            pending.request.x, x * scale + offset, rtol=1e-6
        )


# --------------------------------------------- monitor + SLO chaos (e2e)


class TestMonitorAndSLO:
    @pytest.fixture(scope="class")
    def reference_fp(self):
        fx, fa, fb = _windows(64, seed=11)
        return q.build_fingerprint(fx, fa, fb)

    def _run_stream(self, tmp_path, reference_fp, *, run_id, m=48,
                    doctor=None, **window_kw):
        tel = TelemetryRun(tmp_path, run_id=run_id)
        mon = q.QualityMonitor(
            reference_fp, sample_every=1, min_samples=8, telemetry=tel
        )
        engine = SLOEngine(
            tel.run_dir,
            rules=default_quality_rules(
                fast_window_s=300.0, slow_window_s=300.0
            ),
            sink=tel.sink,
        )
        xs, a, b = _windows(m, **window_kw)
        if doctor is not None:
            a, b = doctor(a, b)
        for i in range(m):
            mon.sample(xs[i], a[i], b[i])
        states = [engine.tick(), engine.tick()]  # for_ticks=2 debounce
        tel.close()
        return mon, states, read_events(tel.run_dir / "events.jsonl")

    def test_iid_twin_stays_silent(self, tmp_path, reference_fp):
        mon, states, events = self._run_stream(
            tmp_path, reference_fp, run_id="q-iid", seed=12
        )
        assert states[-1]["firing"] == []
        assert not any(e["kind"] == "alert_fired" for e in events)
        last = mon.last_scores()
        assert last["scored"] and not last["input_breached"]

    def test_shift_fires_input_drift_alert(self, tmp_path, reference_fp):
        mon, states, events = self._run_stream(
            tmp_path, reference_fp, run_id="q-shift",
            scale=1.6, offset=0.8, seed=13,
        )
        assert "input-drift" in states[-1]["firing"]
        fired = [e for e in events if e["kind"] == "alert_fired"]
        assert any(e["slo_kind"] == "input_drift" for e in fired)
        # The honest-OLS predictions keep the shadow detector quiet, so
        # the sustained-breach-without-alert contract stays clean.
        assert q.quality_violations(events) == []
        rep = q.quality_report(events)
        assert rep["samples"] == 48
        assert rep["breaches"]["input"] > 0
        assert rep["alerts_fired"] >= 1

    def test_garbage_predictions_fire_shadow_alert(
        self, tmp_path, reference_fp
    ):
        mon, states, events = self._run_stream(
            tmp_path, reference_fp, run_id="q-shadow", seed=14,
            doctor=lambda a, b: (a * 40.0 + 3.0, b * 40.0),
        )
        assert "shadow-disagreement" in states[-1]["firing"]
        assert q.quality_violations(events) == []  # breach DID alert
        assert q.quality_report(events)["breaches"]["shadow"] > 0

    def test_live_summaries_gate_on_min_samples(self, reference_fp):
        mon = q.QualityMonitor(reference_fp, sample_every=1, min_samples=4)
        xs, a, b = _windows(6, seed=15)
        for i in range(3):
            mon.sample(xs[i], a[i], b[i])
        assert mon.live_summaries() == {}
        for i in range(3, 6):
            mon.sample(xs[i], a[i], b[i])
        live = mon.live_summaries()
        assert live["sampled"] == 6
        assert live["alpha"]["count"] > 0
        # set_reference re-baselines and restarts the live sketches.
        mon.set_reference(reference_fp)
        assert mon.live_summaries() == {}

    def test_sampling_rate_one_in_k(self, reference_fp):
        mon = q.QualityMonitor(reference_fp, sample_every=4, min_samples=2)
        xs, a, b = _windows(16, seed=16)
        sampled = [
            mon.sample(xs[i], a[i], b[i]) is not None for i in range(16)
        ]
        assert sum(sampled) == 4
        assert sampled[0]  # first delivery is always sampled


# --------------------------------------- checkpoint sidecar (MANIFEST.json)


def _tiny_spec():
    from masters_thesis_tpu.models.objectives import ModelSpec

    return ModelSpec(
        objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
        kernel_impl="xla",
    )


def _init_params(spec, seed=0):
    import jax
    import jax.numpy as jnp

    module = spec.build_module()
    return module.init(
        jax.random.key(seed), jnp.zeros((1, T, F), jnp.float32)
    )["params"]


def _save_ckpt(d, spec, params, epoch, extra_files=None):
    from masters_thesis_tpu.train.checkpoint import save_checkpoint

    save_checkpoint(
        Path(d), "best", params, {}, spec,
        meta={"epoch": epoch, "datamodule": {"lookback_window": T}},
        extra_files=extra_files,
    )


class TestQualitySidecar:
    def _fingerprint_json(self):
        fx, fa, fb = _windows(16, n_stocks=K, lookback=T, n_features=F)
        return q.fingerprint_to_json(q.build_fingerprint(fx, fa, fb))

    def test_sidecar_is_manifest_covered_and_verifies(self, tmp_path):
        from masters_thesis_tpu.train.checkpoint import verify_checkpoint

        spec = _tiny_spec()
        _save_ckpt(
            tmp_path, spec, _init_params(spec), epoch=0,
            extra_files={q.FINGERPRINT_FILENAME: self._fingerprint_json()},
        )
        tree = tmp_path / "best"
        sidecar = tree / q.FINGERPRINT_FILENAME
        assert sidecar.exists()
        manifest = json.loads((tree / "MANIFEST.json").read_text())
        assert q.FINGERPRINT_FILENAME in manifest["files"]
        assert (
            manifest["files"][q.FINGERPRINT_FILENAME]["size"]
            == sidecar.stat().st_size
        )
        assert verify_checkpoint(tree, require_manifest=True)
        assert q.read_fingerprint(tree)["windows"] == 16

    def test_torn_sidecar_fails_strict_verify(self, tmp_path):
        from masters_thesis_tpu.train.checkpoint import verify_checkpoint

        spec = _tiny_spec()
        _save_ckpt(
            tmp_path, spec, _init_params(spec), epoch=0,
            extra_files={q.FINGERPRINT_FILENAME: self._fingerprint_json()},
        )
        tree = tmp_path / "best"
        sidecar = tree / q.FINGERPRINT_FILENAME
        raw = bytearray(sidecar.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        sidecar.write_bytes(bytes(raw))
        assert not verify_checkpoint(tree, require_manifest=True)

    def test_rotation_keeps_prev_sidecar(self, tmp_path):
        spec = _tiny_spec()
        js = self._fingerprint_json()
        _save_ckpt(tmp_path, spec, _init_params(spec, 0), epoch=0,
                   extra_files={q.FINGERPRINT_FILENAME: js})
        _save_ckpt(tmp_path, spec, _init_params(spec, 1), epoch=1,
                   extra_files={q.FINGERPRINT_FILENAME: js})
        assert (tmp_path / "best" / q.FINGERPRINT_FILENAME).exists()
        assert (tmp_path / "best.prev" / q.FINGERPRINT_FILENAME).exists()


# ------------------------------------------------- quality-gated hot-swap


@pytest.fixture
def swap_setup(tmp_path):
    from masters_thesis_tpu.serve.engine import PredictEngine

    d = tmp_path / "ckpts"
    spec = _tiny_spec()
    _save_ckpt(d, spec, _init_params(spec, seed=0), epoch=0)
    engine = PredictEngine.from_checkpoint(
        d, "best", n_stocks=K, n_features=F, buckets=(1,)
    )
    engine.warmup()
    return d, spec, engine


def _candidate_outputs(engine, params):
    """Candidate outputs on the seed-0 golden windows, host-side. One
    window at a time — the fixture engine only compiles bucket 1, which
    is exactly the mismatch the swapper's chunked predict must absorb."""
    gx = q.golden_windows(8, K, T, F, seed=0)
    dev = engine.put_params(params)
    outs = [engine.predict(gx[i : i + 1], params=dev) for i in range(len(gx))]
    ga = np.concatenate([np.asarray(o[0]) for o in outs])
    gb = np.concatenate([np.asarray(o[1]) for o in outs])
    return gx, ga, gb


class TestSwapQualityGate:
    def test_honest_fingerprint_commits_and_rebaselines(
        self, swap_setup, tmp_path
    ):
        from masters_thesis_tpu.serve.swap import CheckpointSwapper

        d, spec, engine = swap_setup
        mon = q.QualityMonitor(None, sample_every=1)
        tel = TelemetryRun(tmp_path / "tel", run_id="swap-q-ok")
        swapper = CheckpointSwapper(
            engine, telemetry=tel, quality_monitor=mon
        )
        cand = _init_params(spec, seed=7)
        gx, ga, gb = _candidate_outputs(engine, cand)
        fp = q.build_fingerprint(
            gx, ga, gb, golden=(gx, ga, gb), golden_seed=0
        )
        _save_ckpt(
            d, spec, cand, epoch=1,
            extra_files={q.FINGERPRINT_FILENAME: q.fingerprint_to_json(fp)},
        )
        verdict = swapper.try_swap(d)
        tel.close()
        assert verdict.ok and verdict.reason == "committed"
        # The gate actually ran: its scores ride on the commit verdict.
        assert "quality_self_ks" in verdict.checks
        assert verdict.checks["quality_self_ks"] < q.GATE_MAX_SELF_KS
        # A committed swap re-baselines the live monitor to the NEW
        # fingerprint (an intentional retrain must not alarm against the
        # old model's sketches).
        assert mon.reference is not None
        assert mon.reference["golden"]["seed"] == 0
        committed = [
            e for e in read_events(tel.run_dir / "events.jsonl")
            if e["kind"] == "swap_committed"
        ]
        assert len(committed) == 1

    def test_diverged_finetune_rejected_with_named_reason(
        self, swap_setup, tmp_path
    ):
        from masters_thesis_tpu.serve.swap import CheckpointSwapper

        d, spec, engine = swap_setup
        tel = TelemetryRun(tmp_path / "tel", run_id="swap-q-bad")
        swapper = CheckpointSwapper(engine, telemetry=tel)
        before = engine.predict(swapper.golden_x)
        cand = _init_params(spec, seed=8)
        gx, ga, gb = _candidate_outputs(engine, cand)
        # The shipped fingerprint claims output sketches the candidate
        # does NOT produce — the diverged-between-fingerprint-and-deploy
        # case the gate exists to catch.
        fp = q.build_fingerprint(
            gx, ga * 50.0 + 5.0, gb * 50.0,
            golden=(gx, ga * 50.0 + 5.0, gb * 50.0), golden_seed=0,
        )
        _save_ckpt(
            d, spec, cand, epoch=1,
            extra_files={q.FINGERPRINT_FILENAME: q.fingerprint_to_json(fp)},
        )
        verdict = swapper.try_swap(d)
        tel.close()
        assert not verdict.ok
        assert verdict.reason.startswith("quality_")
        assert swapper.rejected == 1 and swapper.committed == 0
        # Output parity: the replica keeps serving the exact old params.
        after = engine.predict(swapper.golden_x)
        assert np.array_equal(np.asarray(before[0]), np.asarray(after[0]))
        events = read_events(tel.run_dir / "events.jsonl")
        rejected = [e for e in events if e["kind"] == "swap_rejected"]
        assert len(rejected) == 1
        assert rejected[0]["reason"].startswith("quality_")
        assert "quality_self_ks" in rejected[0]["checks"]
        # The quality section of the post-hoc report names the rejection.
        rep = summarize_events(events)
        assert rep["quality"]["swaps_rejected_quality"] == 1
        assert rep["quality"]["last_rejection"]["reason"].startswith(
            "quality_"
        )

    def test_legacy_checkpoint_without_fingerprint_still_commits(
        self, swap_setup
    ):
        from masters_thesis_tpu.serve.swap import CheckpointSwapper

        d, spec, engine = swap_setup
        swapper = CheckpointSwapper(engine)  # no monitor attached
        _save_ckpt(d, spec, _init_params(spec, seed=7), epoch=1)
        verdict = swapper.try_swap(d)
        assert verdict.ok and verdict.reason == "committed"
        # No fingerprint and no live sketch: the gate never scored.
        assert "quality_self_ks" not in verdict.checks


# ------------------------------------------------ trainer fingerprinting


@pytest.mark.slow
class TestTrainerFingerprint:
    def test_fit_ships_quality_sidecar(self, tmp_path):
        from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule
        from masters_thesis_tpu.data.synthetic import SyntheticLogReturns
        from masters_thesis_tpu.models.objectives import ModelSpec
        from masters_thesis_tpu.train import Trainer
        from masters_thesis_tpu.train.checkpoint import verify_checkpoint

        data_dir = tmp_path / "data"
        data_dir.mkdir()
        r_stocks, r_market, alphas, betas = SyntheticLogReturns.generate(
            n_stocks=8, n_samples=2000, seed=1
        )
        np.save(data_dir / "stocks.npy", np.asarray(r_stocks))
        np.save(data_dir / "market.npy", np.asarray(r_market))
        np.save(data_dir / "alphas.npy", np.asarray(alphas))
        np.save(data_dir / "betas.npy", np.asarray(betas))
        dm = FinancialWindowDataModule(
            data_dir, lookback_window=16, target_window=8, stride=24,
            batch_size=2,
        )
        dm.prepare_data(verbose=False)
        dm.setup()
        tel = TelemetryRun(tmp_path / "tel", run_id="fp-fit")
        trainer = Trainer(
            max_epochs=1, check_val_every_n_epoch=1,
            enable_progress_bar=False, enable_model_summary=False,
            seed=0, strategy="tpu_xla", telemetry=tel,
            ckpt_dir=tmp_path / "ckpts",
        )
        spec = ModelSpec(
            objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
            learning_rate=1e-2,
        )
        trainer.fit(spec, dm)
        tel.close()
        trees = [
            p for p in (tmp_path / "ckpts").iterdir()
            if p.is_dir() and not p.name.endswith(".prev")
        ]
        assert trees, "fit saved no checkpoint"
        for tree in trees:
            fp = q.read_fingerprint(tree)
            assert fp is not None, f"{tree.name} shipped no quality.json"
            assert fp["windows"] > 0
            assert fp["golden"]["shape"][0] == 32  # trainer's golden count
            assert fp["golden"]["shape"][2] == 16  # lookback window
            manifest = json.loads((tree / "MANIFEST.json").read_text())
            assert q.FINGERPRINT_FILENAME in manifest["files"]
            assert verify_checkpoint(tree, require_manifest=True)
        events = read_events(tel.run_dir / "events.jsonl")
        fp_events = [e for e in events if e["kind"] == "quality_fingerprint"]
        assert fp_events and fp_events[0]["windows"] > 0


# ------------------------------------------------- CLI + report surfaces


class TestQualityCLI:
    def test_selfcheck(self):
        assert cli_main(["quality", "--selfcheck"]) == 0

    def test_missing_root_errors(self, tmp_path):
        assert cli_main(["quality", str(tmp_path / "nope")]) == 1
        assert cli_main(["quality"]) == 1

    def _emit(self, tel, n, **overrides):
        base = dict(
            scored=True, input_psi=0.01, input_ks=0.01, pred_psi=0.01,
            pred_ks=0.01, shadow_err=0.05, input_thr=0.25, pred_thr=0.25,
            shadow_thr=0.5, input_breached=False, pred_breached=False,
            shadow_breached=False,
        )
        base.update(overrides)
        for i in range(n):
            tel.event("quality_sample", sampled=i + 1, **base)

    def test_clean_run_exits_zero(self, tmp_path):
        tel = TelemetryRun(tmp_path, run_id="q-clean")
        self._emit(tel, 4)
        tel.close()
        assert cli_main(["quality", str(tmp_path)]) == 0
        assert cli_main(["quality", str(tmp_path), "--json"]) == 0

    def test_breach_without_alert_is_a_violation_exit_2(
        self, tmp_path, capsys
    ):
        tel = TelemetryRun(tmp_path, run_id="q-viol")
        self._emit(tel, 4, shadow_err=0.9, shadow_breached=True)
        tel.event("slo_snapshot")  # an SLO engine WAS attached
        tel.close()
        events = read_events(tmp_path / "events.jsonl")
        assert len(q.quality_violations(events)) == 1
        assert cli_main(["quality", str(tmp_path)]) == 2
        out = capsys.readouterr().out
        assert "QUALITY" in out
        assert "CONTRACT VIOLATION" in out
        # --json carries the same verdict machine-readably.
        assert cli_main(["quality", str(tmp_path), "--json"]) == 2
        payload = json.loads(capsys.readouterr().out)
        assert payload["violations"]
        assert payload["quality"]["breaches"]["shadow"] == 4
        # The same violation surfaces in the full summarize report.
        rep = summarize_events(events)
        assert any("shadow" in v for v in rep["violations"])

    def test_alerted_breach_is_not_a_violation(self, tmp_path):
        tel = TelemetryRun(tmp_path, run_id="q-alerted")
        self._emit(tel, 4, shadow_err=0.9, shadow_breached=True)
        tel.event("slo_snapshot")
        tel.event(
            "alert_fired", rule="shadow-disagreement",
            slo_kind="shadow_disagreement", value=0.9, threshold=0.5,
        )
        tel.close()
        events = read_events(tmp_path / "events.jsonl")
        assert q.quality_violations(events) == []
        # Exit is still 2 — a breach is a breach — but with no violation.
        assert cli_main(["quality", str(tmp_path)]) == 2

    def test_render_marks_breaches(self):
        rep = q.quality_report(
            [
                {
                    "kind": "quality_sample", "sampled": 1, "scored": True,
                    "input_psi": 0.31, "pred_psi": 0.02, "shadow_err": 0.1,
                    "input_breached": True, "pred_breached": False,
                    "shadow_breached": False,
                }
            ]
        )
        line = q.render_quality(rep)
        assert line.startswith("QUALITY")
        assert "input_psi=0.310!" in line
        assert "pred_psi=0.020" in line and "pred_psi=0.020!" not in line
        assert q.render_quality({}) == "QUALITY   (no sampled windows)"
