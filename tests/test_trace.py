"""Distributed tracing (ISSUE 12): span writer, cross-process trace
propagation, open-span recovery past SIGKILL, and the jax-free ``trace``
CLI (Perfetto export + critical-path attribution).

The subprocess scenarios reuse tests/_fleet_worker.py (jax-free) so the
propagation tests exercise exactly the env contract real supervisors and
fleets use: ``MTT_TRACE_ID`` carries the trace, ``MTT_PARENT_SPAN`` the
parent span id.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from masters_thesis_tpu.resilience.supervisor import (
    RunSupervisor,
    SupervisorConfig,
)
from masters_thesis_tpu.telemetry.__main__ import main as cli_main
from masters_thesis_tpu.telemetry.aggregate import aggregate_path
from masters_thesis_tpu.telemetry.events import EventSink, read_events
from masters_thesis_tpu.telemetry.run import TelemetryRun
from masters_thesis_tpu.telemetry.trace import (
    PARENT_SPAN_ENV,
    TRACE_ENV,
    Tracer,
    build_trace_report,
    child_env,
    collect_spans,
    new_trace_id,
    validate_spans,
)

_REPO_ROOT = Path(__file__).resolve().parent.parent
_WORKER = _REPO_ROOT / "tests" / "_fleet_worker.py"


def _spans(path: Path) -> list[dict]:
    return [e for e in read_events(path) if e.get("kind") == "span"]


# ------------------------------------------------------------------ writer


class TestTracer:
    def test_span_event_schema_and_nesting(self, tmp_path):
        sink = EventSink(tmp_path / "events.jsonl", run_id="t")
        tr = Tracer(sink, env={})
        outer = tr.start("trainer.fit", trainer="test")
        inner = tr.start("train.eval", parent=outer, epoch=3)
        tr.end(inner)
        tr.end(outer, status="ok", epochs=1)
        sink.close()
        spans = _spans(tmp_path / "events.jsonl")
        assert [s["name"] for s in spans] == ["trainer.fit", "train.eval"][
            ::-1
        ]  # close order: inner first
        by_name = {s["name"]: s for s in spans}
        fit, ev = by_name["trainer.fit"], by_name["train.eval"]
        assert ev["parent_id"] == fit["span_id"]
        assert ev["trace_id"] == fit["trace_id"] == tr.trace_id
        assert fit["parent_id"] is None and not fit["ext"]
        assert ev["attrs"]["epoch"] == 3
        assert fit["attrs"] == {"trainer": "test", "epochs": 1}
        assert fit["dur_s"] >= 0 and fit["status"] == "ok"
        # cat defaults to the name's first dotted segment.
        assert fit["cat"] == "trainer" and ev["cat"] == "train"

    def test_context_manager_marks_errors(self, tmp_path):
        sink = EventSink(tmp_path / "events.jsonl", run_id="t")
        tr = Tracer(sink, env={})
        with pytest.raises(ValueError):
            with tr.span("serve.batch"):
                raise ValueError("boom")
        sink.close()
        (span,) = _spans(tmp_path / "events.jsonl")
        assert span["status"] == "error"

    def test_env_round_trip_is_not_an_orphan(self, tmp_path):
        parent_sink = EventSink(
            tmp_path / "parent" / "events.jsonl", run_id="parent"
        )
        tr1 = Tracer(parent_sink, env={})
        root = tr1.start("supervisor.run")
        env = child_env(parent=root, env={}, trace_id=tr1.trace_id)
        assert env[TRACE_ENV] == tr1.trace_id
        assert env[PARENT_SPAN_ENV] == root.span_id

        child_sink = EventSink(
            tmp_path / "child" / "events.jsonl", run_id="child"
        )
        tr2 = Tracer(child_sink, env=env)
        assert tr2.trace_id == tr1.trace_id
        fit = tr2.start("trainer.fit")
        assert fit.parent_id == root.span_id and fit.ext
        tr2.end(fit)
        tr1.end(root)
        child_sink.close()
        parent_sink.close()
        # The child stream READ ALONE must not flag its env-external root
        # as an orphan — the parent's stream may be out of scope.
        collected = collect_spans(tmp_path / "child")
        assert validate_spans(collected["spans"], collected["problems"]) == []

    def test_close_all_closes_children_before_parents(self, tmp_path):
        sink = EventSink(tmp_path / "events.jsonl", run_id="t")
        tr = Tracer(sink, env={})
        outer = tr.start("a.outer")
        time.sleep(0.01)
        tr.start("a.inner", parent=outer)
        assert tr.close_all(status="aborted") == 2
        sink.close()
        spans = _spans(tmp_path / "events.jsonl")
        assert [s["name"] for s in spans] == ["a.inner", "a.outer"]
        assert all(s["status"] == "aborted" for s in spans)

    def test_telemetry_run_close_aborts_open_spans(self, tmp_path):
        tel = TelemetryRun(tmp_path, run_id="t")
        tel.tracer.start("trainer.fit")
        tel.close()
        (span,) = _spans(tmp_path / "events.jsonl")
        assert span["name"] == "trainer.fit"
        assert span["status"] == "aborted"

    def test_reused_run_dir_adopts_predecessor_open_spans(self, tmp_path):
        """A supervised retry resuming IN PLACE re-opens the same run dir
        and overwrites the dead attempt's heartbeat — the only record of
        its open fit span. attach_flight_recorder must close that span
        into the stream first, or the dead attempt's epoch spans orphan
        (found on a real supervised train run with an injected SIGKILL)."""
        tel1 = TelemetryRun(tmp_path, run_id="a1")
        rec1 = tel1.attach_flight_recorder(
            install_signal_handlers=False,
            enable_faulthandler=False,
            heartbeat_interval_s=60.0,
        )
        fit = tel1.tracer.start("trainer.fit")
        tel1.tracer.emit_span(
            "train.epoch", start_ts=time.time(), dur_s=0.1, parent=fit,
            epoch=0, dispatch_s=0.01, data_wait_s=0.0,
        )
        rec1._write_heartbeat()
        rec1._closed.set()  # stop the beat thread: SIGKILL writes nothing

        tel2 = TelemetryRun(tmp_path, run_id="a2")
        tel2.attach_flight_recorder(
            install_signal_handlers=False,
            enable_faulthandler=False,
            heartbeat_interval_s=60.0,
        )
        with tel2.tracer.span("trainer.fit"):
            pass
        tel2.close()

        collected = collect_spans(tmp_path)
        assert validate_spans(collected["spans"], collected["problems"]) == []
        adopted = next(
            s for s in collected["spans"] if s["span_id"] == fit.span_id
        )
        assert adopted["status"] == "aborted"
        assert adopted["attrs"]["synthesized"] is True

    def test_flight_recorder_sidecars_carry_open_spans(self, tmp_path):
        tel = TelemetryRun(tmp_path, run_id="t")
        rec = tel.attach_flight_recorder(
            install_signal_handlers=False,
            enable_faulthandler=False,
            heartbeat_interval_s=60.0,
        )
        span = tel.tracer.start("trainer.fit", trainer="test")
        rec.dump("signal:SIGTERM (test)")
        dump = json.loads((tmp_path / "crashdump.json").read_text())
        names = [s["name"] for s in dump["open_spans"]]
        assert names == ["trainer.fit"]
        assert dump["open_spans"][0]["span_id"] == span.span_id
        tel.close()
        hb = json.loads((tmp_path / "heartbeat.json").read_text())
        # close_all ran before the final heartbeat: nothing left open.
        assert hb["closed"] is True and hb["open_spans"] == []


# --------------------------------------------------------------- trace CLI


def _write_epoch_stream(root: Path, trace_id: str, walls=(0.5, 0.4, 0.6)):
    sink = EventSink(root / "events.jsonl", run_id="run")
    tr = Tracer(sink, env={TRACE_ENV: trace_id})
    fit = tr.start("trainer.fit")
    t0 = time.time() - 60.0
    for ep, wall in enumerate(walls):
        tr.emit_span(
            "train.epoch", start_ts=t0 + ep, dur_s=wall, parent=fit,
            epoch=ep, dispatch_s=0.04 * wall, data_wait_s=0.01 * wall,
        )
    tr.end(fit)
    sink.close()


class TestTraceCli:
    def test_report_and_chrome_export(self, tmp_path, capsys):
        trace_id = new_trace_id()
        _write_epoch_stream(tmp_path / "run", trace_id)
        out = tmp_path / "trace.json"
        assert cli_main(
            ["trace", str(tmp_path / "run"), "--out", str(out)]
        ) == 0
        text = capsys.readouterr().out
        assert "span tree      : ok" in text
        assert "epoch median" in text
        chrome = json.loads(out.read_text())
        events = chrome["traceEvents"]
        assert all({"ph", "pid"} <= set(e) for e in events)
        x_events = [e for e in events if e["ph"] == "X"]
        assert {e["name"] for e in x_events} == {"trainer.fit", "train.epoch"}
        assert any(
            e["ph"] == "M" and e["name"] == "process_name" for e in events
        )
        report = build_trace_report(tmp_path / "run")
        med = report["epoch"]["median"]
        assert med["sum_ok"] and med["epoch"] == 0  # 0.5 is the median wall
        assert med["wall_s"] == pytest.approx(0.5)
        comp = med["components_s"]
        assert sum(comp.values()) == pytest.approx(0.5)

    def test_no_spans_exits_1(self, tmp_path):
        (tmp_path / "events.jsonl").write_text("")
        assert cli_main(["trace", str(tmp_path)]) == 1
        assert cli_main(["trace", str(tmp_path / "missing")]) == 1

    def test_broken_tree_exits_2(self, tmp_path):
        sink = EventSink(tmp_path / "events.jsonl", run_id="bad")
        tr = Tracer(sink, env={})
        tr.emit_span("x.orphan", start_ts=1.0, dur_s=1.0, parent="feedfeed")
        tr.emit_span("x.negative", start_ts=2.0, dur_s=-0.5)
        sink.close()
        assert cli_main(["trace", str(tmp_path)]) == 2
        report = build_trace_report(tmp_path)
        assert {p["kind"] for p in report["problems"]} == {
            "orphan", "negative_duration",
        }

    def test_selfcheck_green(self, capsys):
        assert cli_main(["trace", "--selfcheck"]) == 0
        assert "trace selfcheck: ok" in capsys.readouterr().out


# ------------------------------------------------- serve path attribution


class TestServeTracing:
    """Jax-free: the fake engine from the serve selfcheck drives the REAL
    queue/admission/dispatch loop, so the per-request spans and their
    component tiling are exactly what production emits."""

    def _server(self, tmp_path, **kwargs):
        from masters_thesis_tpu.serve.__main__ import _FakeEngine
        from masters_thesis_tpu.serve.server import PredictServer

        tel = TelemetryRun(tmp_path / "serve", run_id="serve-test")
        engine = _FakeEngine(service_s=0.002)
        server = PredictServer(engine, telemetry=tel, **kwargs)
        return tel, engine, server

    def test_request_spans_tile_the_wall(self, tmp_path):
        tel, engine, server = self._server(tmp_path, max_wait_s=0.001)
        server.start()
        x = np.zeros(engine.window_shape, np.float32)
        pending = [server.submit(x, deadline_s=5.0) for _ in range(12)]
        results = [p.result(timeout=10.0) for p in pending]
        stats = server.stop()
        tel.close()
        assert all(r.ok for r in results)
        assert 0.0 <= stats["queue_wait_share"] <= 1.0
        assert 0.0 < stats["compute_share"] <= 1.0

        report = build_trace_report(tmp_path)
        assert report["exit_code"] == 0
        serve = report["serve"]
        assert serve["requests"] == 12 and serve["completed"] == 12
        for which in ("p50", "p99"):
            b = serve[which]
            assert b["sum_ok"], f"{which} components do not cover wall: {b}"
            assert sum(b["components_s"].values()) == pytest.approx(
                b["wall_s"]
            )
        # The batch-level device span rides the server root span.
        spans = collect_spans(tmp_path)["spans"]
        device = [s for s in spans if s["name"] == "serve.device"]
        server_span = next(s for s in spans if s["name"] == "serve.server")
        assert device
        assert all(s["parent_id"] == server_span["span_id"] for s in device)

    def test_shed_categorized_and_closed_as_shed(self, tmp_path):
        tel, engine, server = self._server(tmp_path)
        server.start()
        server.service_model.seed(10.0)  # force infeasible deadlines
        x = np.zeros(engine.window_shape, np.float32)
        r = server.submit(x, deadline_s=0.01).result(timeout=5.0)
        assert r.status == "shed"
        stats = server.stop()
        tel.close()
        assert stats["shed_by_reason"] == {"deadline_infeasible": 1}
        report = build_trace_report(tmp_path)
        assert report["exit_code"] == 0
        assert report["serve"]["shed"] == 1
        assert report["serve"]["shed_by_reason"] == {
            "deadline_infeasible": 1,
        }


# ------------------------------------------- cross-process propagation


def _spawn(root: Path, rank: int, scenario: str, env: dict):
    return subprocess.Popen(
        [sys.executable, str(_WORKER), str(root), str(rank), "2", scenario],
        cwd=_REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


class TestPropagation:
    def test_sigkill_mid_epoch_aborts_open_spans(self, tmp_path):
        """SIGKILL leaves no crashdump — the periodic heartbeat is the
        only record of the victim's open fit span. The trace CLI must
        close it as ``aborted`` (exit 0), never flag it orphaned."""
        trace_id = new_trace_id()
        env = {
            **os.environ,
            "PYTHONPATH": str(_REPO_ROOT),
            TRACE_ENV: trace_id,
        }
        p0 = _spawn(tmp_path, 0, "healthy", env)
        p1 = _spawn(tmp_path, 1, "victim-sigterm", env)
        try:
            line = p1.stdout.readline().strip()
            assert line == "ready", f"worker said {line!r}"
            time.sleep(0.4)  # let a heartbeat flush the open fit span
            p1.kill()  # SIGKILL: no handler, no crashdump
            p1.wait(timeout=30)
            assert p0.wait(timeout=30) == 0
        finally:
            for p in (p0, p1):
                if p.poll() is None:
                    p.kill()
                    p.wait()
        hb = json.loads((tmp_path / "p1" / "heartbeat.json").read_text())
        assert not hb.get("closed")
        assert any(
            s["name"] == "trainer.fit" for s in hb["open_spans"]
        )
        out = tmp_path / "trace.json"
        report = build_trace_report(tmp_path, out=out)
        assert report["exit_code"] == 0, report["problems"]
        assert report["aborted"] >= 1
        aborted = [
            s for s in collect_spans(tmp_path)["spans"]
            if s["status"] == "aborted"
        ]
        assert any(s["name"] == "trainer.fit" for s in aborted)
        # ONE trace id across both processes, adopted from the env.
        assert list(report["traces"]) == [trace_id]
        assert report["traces"][trace_id]["streams"] == ["p0", "p1"]
        assert json.loads(out.read_text())["traceEvents"]

    def test_fleet_span_merge_and_wait_attribution(self, tmp_path):
        trace_id = new_trace_id()
        env = {
            **os.environ,
            "PYTHONPATH": str(_REPO_ROOT),
            TRACE_ENV: trace_id,
        }
        procs = [_spawn(tmp_path, r, "healthy", env) for r in (0, 1)]
        try:
            assert all(p.wait(timeout=30) == 0 for p in procs)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        report = aggregate_path(tmp_path)
        assert report["trace_ids"] == [trace_id]
        # Rank-skewed walls (0.05 vs 0.10 over 3 shared epochs): p0 waits
        # on p1 in every epoch, attributed to the NAMED epoch span.
        waits = report["collective_wait_by_span_s"]["train.epoch"]
        assert waits["p0"] == pytest.approx(0.15, abs=0.01)
        assert waits["p1"] == pytest.approx(0.0, abs=0.01)

    def test_supervised_restart_keeps_one_trace_id(self, tmp_path, capsys):
        """The supervisor propagates ONE stable trace id FORWARD through
        every retry; each attempt hangs off its own supervisor.attempt
        span via MTT_PARENT_SPAN."""
        log = tmp_path / "attempt_env.log"
        code = (
            "import os, sys; "
            "open(sys.argv[1], 'a').write("
            "os.environ.get('MTT_TRACE_ID', '') + ' ' "
            "+ os.environ.get('MTT_PARENT_SPAN', '') + '\\n'); "
            "print('RuntimeError: boom-' + os.environ['MTT_ATTEMPT'], "
            "file=sys.stderr); "
            "sys.exit(9)"
        )
        sup = RunSupervisor(
            [sys.executable, "-c", code, str(log)],
            run_dir=tmp_path / "sup",
            cfg=SupervisorConfig(
                max_retries=1, backoff_s=0.05, backoff_factor=1.0
            ),
        )
        res = sup.run()
        assert not res.ok and res.n_attempts == 2

        lines = [ln.split() for ln in log.read_text().splitlines()]
        assert len(lines) == 2
        (tid1, parent1), (tid2, parent2) = lines
        assert tid1 == tid2 == sup.trace_id
        assert parent1 and parent2 and parent1 != parent2

        events = read_events(tmp_path / "sup" / "events.jsonl")
        spans = [e for e in events if e.get("kind") == "span"]
        by_name: dict[str, list] = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        assert len(by_name["supervisor.attempt"]) == 2
        assert len(by_name["supervisor.run"]) == 1
        run_span = by_name["supervisor.run"][0]
        assert all(
            s["parent_id"] == run_span["span_id"]
            and s["trace_id"] == sup.trace_id
            for s in by_name["supervisor.attempt"]
        )
        # Each attempt's exported parent is its own attempt span.
        assert {parent1, parent2} == {
            s["span_id"] for s in by_name["supervisor.attempt"]
        }
        started = [e for e in events if e.get("kind") == "attempt_started"]
        assert all(e.get("trace_id") == sup.trace_id for e in started)
        # The summarize restarts line names the trace stitching the chain.
        cli_main(["summarize", str(tmp_path / "sup")])
        assert f"trace {sup.trace_id}" in capsys.readouterr().out
