"""Fama-French CSV ingestion against synthetic fixture files that replicate
the Ken French data-library layout (preamble lines, sentinel rows)."""

import numpy as np
import pytest

from masters_thesis_tpu.data import FamaFrench25Portfolios as FF


def _write_fixtures(tmp_path, n_rows, sentinel_rows=()):
    """Build ff3 + p25 CSVs with deterministic values: row i has
    Mkt-RF = 0.01*i, RF = 0.001*i, portfolio j value = 0.01*i + 0.1*j.
    Sentinel rows carry -99.99 in every portfolio column with a NONZERO RF —
    the loader must catch them on the raw values (the reference's
    mask-after-RF-subtraction misses exactly this case)."""
    ff3_lines = ["preamble"] * FF.ff3_skip
    ff3_lines.append(",".join(FF.ff3_cols))
    p25_lines = ["preamble"] * FF.p25_skip
    p25_lines.append(",".join(f'"{c}"' for c in FF.p25_cols))
    for i in range(n_rows):
        date = 19260700 + i
        ff3_lines.append(f"{date},{0.01 * i:.4f},0.0,0.0,{0.001 * i:.4f}")
        if i in sentinel_rows:
            vals = ["-99.99"] * 25
        else:
            vals = [f"{0.01 * i + 0.1 * j:.4f}" for j in range(25)]
        p25_lines.append(f"{date}," + ",".join(vals))
    (tmp_path / FF.ff3_filename).write_text("\n".join(ff3_lines) + "\n")
    (tmp_path / FF.p25_filename).write_text("\n".join(p25_lines) + "\n")


def test_load_shapes_and_values(tmp_path):
    n_rows = FF.skip_old_data + 500
    _write_fixtures(tmp_path, n_rows)
    p25, mkt = FF.load(tmp_path)

    assert p25.shape[0] == 25
    assert p25.shape[1] == mkt.shape[0]
    assert p25.dtype == np.float32

    # Independent oracle: skiprows covers the preamble + real header + data
    # rows 0..skip_old_data-2, and the next data row is consumed as the
    # pandas header — so the first surviving row is i = skip_old_data.
    i0 = FF.skip_old_data
    expected_mkt0 = 100.0 * (np.log(0.01 * i0 + 100.0) - np.log(100.0))
    np.testing.assert_allclose(mkt[0], expected_mkt0, rtol=1e-5)
    # Portfolio 3, first row: (0.01*i0 + 0.3) - RF, then log transform.
    raw = (0.01 * i0 + 0.3) - 0.001 * i0
    expected_p25 = 100.0 * (np.log(raw + 100.0) - np.log(100.0))
    np.testing.assert_allclose(p25[3, 0], expected_p25, rtol=1e-5)


def test_load_masks_sentinel_rows(tmp_path):
    i0 = FF.skip_old_data
    bad = {i0 + 5, i0 + 17}
    n_rows = FF.skip_old_data + 300
    _write_fixtures(tmp_path, n_rows, sentinel_rows=bad)
    p25_clean, mkt_clean = FF.load(tmp_path)

    _write_fixtures(tmp_path, n_rows)  # same file without sentinels
    p25_full, mkt_full = FF.load(tmp_path)

    assert mkt_clean.shape[0] == mkt_full.shape[0] - len(bad)
    assert np.all(np.isfinite(p25_clean)), "surviving rows must be NaN-free"


def test_sentinel_on_nonzero_rf_day_pins_reference_deviation(tmp_path):
    """Pin the DIRECTION of the conscious deviation from the reference
    (src/data.py:112-115): the reference masks sentinels AFTER subtracting
    RF, so on a day with nonzero RF the value ``-99.99 - RF`` no longer
    equals the sentinel, escapes the reference's mask, and
    ``log(-99.99 - RF + 100)`` goes NaN. This loader masks on the RAW
    values and drops the row. Net effect vs the reference on such a day:
    exactly one fewer (clean) sample instead of one NaN-poisoned sample."""
    i0 = FF.skip_old_data
    bad_day = i0 + 3
    n_rows = FF.skip_old_data + 200
    _write_fixtures(tmp_path, n_rows, sentinel_rows={bad_day})
    p25, mkt = FF.load(tmp_path)
    _write_fixtures(tmp_path, n_rows)  # same data, no sentinel
    p25_full, mkt_full = FF.load(tmp_path)

    rf = 0.001 * bad_day  # the fixture's RF on the sentinel day — nonzero
    assert rf > 0 and (-99.99 - rf) != -99.99  # escapes the reference mask
    # The reference's log transform on the escaped value injects NaN:
    with np.errstate(invalid="ignore"):
        ref_value = 100.0 * (np.log(-99.99 - rf + 100.0) - np.log(100.0))
    assert not np.isfinite(ref_value)
    # This loader instead drops the day — one fewer sample, all finite.
    assert mkt.shape[0] == mkt_full.shape[0] - 1
    assert np.all(np.isfinite(p25)) and np.all(np.isfinite(mkt))


def test_load_missing_file_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        FF.load(tmp_path)
