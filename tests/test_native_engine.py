"""Parity tests: native C++ window engine vs the pure-JAX pipeline path.

The native engine must produce the same dataset the jnp pipeline does
(reference semantics: src/common.py:81-148 composed by src/data.py:196-214),
within float32 rounding — both paths feed the same training stack.
"""

import numpy as np
import pytest

from masters_thesis_tpu import native
from masters_thesis_tpu.ops import (
    add_quadratic_features,
    lookback_target_split,
    ols_features,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ compiler / cached native build"
)


def _series(rng, k=7, t=500):
    stocks = rng.normal(0.01, 0.5, size=(k, t)).astype(np.float32)
    market = rng.normal(0.02, 0.4, size=(t,)).astype(np.float32)
    return stocks, market


@pytest.mark.parametrize("interaction_only", [True, False])
@pytest.mark.parametrize("prediction", [True, False])
def test_matches_jnp_pipeline(rng, interaction_only, prediction):
    stocks, market = _series(rng)
    kw = dict(lookback_window=24, target_window=12, stride=20)

    out = native.build_dataset(
        stocks, market, prediction=prediction,
        interaction_only=interaction_only, **kw,
    )
    x_ref, y_ref = lookback_target_split(
        stocks, market, prediction=prediction,
        lookback_window=kw["lookback_window"],
        target_window=kw["target_window"], stride=kw["stride"],
    )
    x_ref = add_quadratic_features(x_ref, interaction_only=interaction_only)
    a_ref, b_ref, f_ref, ip_ref = ols_features(y_ref)

    np.testing.assert_array_equal(out["x"], np.asarray(x_ref))
    np.testing.assert_array_equal(out["y"], np.asarray(y_ref))
    np.testing.assert_allclose(out["alphas"], np.asarray(a_ref), atol=2e-5)
    np.testing.assert_allclose(out["betas"], np.asarray(b_ref), atol=2e-4)
    np.testing.assert_allclose(out["factor"], np.asarray(f_ref), rtol=2e-5)
    np.testing.assert_allclose(out["inv_psi"], np.asarray(ip_ref), rtol=2e-3)


def test_degenerate_constant_market_matches_pinv(rng):
    """Constant market regressor: native must match pinv's min-norm solution."""
    k, t = 3, 64
    stocks = rng.normal(0.01, 0.5, size=(k, t)).astype(np.float32)
    market = np.full((t,), 0.25, np.float32)
    kw = dict(lookback_window=16, target_window=16, stride=32)

    out = native.build_dataset(stocks, market, **kw)
    _, y_ref = lookback_target_split(
        stocks, market, prediction=True,
        lookback_window=16, target_window=16, stride=32,
    )
    a_ref, b_ref, _, _ = ols_features(y_ref)
    np.testing.assert_allclose(out["alphas"], np.asarray(a_ref), atol=1e-5)
    np.testing.assert_allclose(out["betas"], np.asarray(b_ref), atol=1e-5)


def test_num_windows_edges():
    assert native.num_windows(100, 90, 90) == 1
    assert native.num_windows(180, 90, 90) == 2
    assert native.num_windows(89, 90, 90) == -1
    assert native.num_windows(100, 90, 5) == 3


def test_single_thread_matches_parallel(rng):
    stocks, market = _series(rng, k=3, t=400)
    kw = dict(lookback_window=16, target_window=8, stride=10)
    a = native.build_dataset(stocks, market, n_threads=1, **kw)
    b = native.build_dataset(stocks, market, n_threads=8, **kw)
    for key in a:
        np.testing.assert_array_equal(a[key], b[key])


def test_multihost_nonwriter_waits_for_published_cache(rng, tmp_path, monkeypatch):
    """Non-zero processes must poll for process 0's cache, not rebuild it."""
    import threading

    from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule

    stocks, market = _series(rng, k=3, t=400)
    np.save(tmp_path / "stocks.npy", stocks)
    np.save(tmp_path / "market.npy", market)
    kw = dict(lookback_window=16, target_window=8, stride=24)

    import jax

    # This thread plays process 1 (non-writer); the spawned thread plays
    # process 0 (the writer).
    main_tid = threading.get_ident()
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        jax,
        "process_index",
        lambda: 1 if threading.get_ident() == main_tid else 0,
    )
    dm = FinancialWindowDataModule(tmp_path, **kw)

    # A writer publishing concurrently unblocks the wait.
    writer_dm = FinancialWindowDataModule(tmp_path, **kw)
    t = threading.Thread(
        target=lambda: writer_dm.prepare_data(verbose=False)
    )
    t.start()
    dm.prepare_data(verbose=False, cache_timeout_s=30.0)
    t.join()
    dm.setup()
    assert dm.train_arrays().x.shape[-1] == 3


def test_multihost_hostlocal_dir_builds_own_cache(rng, tmp_path, monkeypatch):
    """A non-zero process whose data_dir is host-local (no shared writer)
    must build its own per-host cache after the wait times out."""
    from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule

    stocks, market = _series(rng, k=3, t=400)
    np.save(tmp_path / "stocks.npy", stocks)
    np.save(tmp_path / "market.npy", market)

    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    dm = FinancialWindowDataModule(
        tmp_path, lookback_window=16, target_window=8, stride=24
    )
    dm.prepare_data(verbose=False, cache_timeout_s=1.0)
    dm.setup()
    assert dm.train_arrays().x.shape[-1] == 3


def test_datamodule_native_equals_python(rng, tmp_path):
    from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule

    stocks, market = _series(rng, k=4, t=600)
    for sub in ("nat", "py"):
        d = tmp_path / sub
        d.mkdir()
        np.save(d / "stocks.npy", stocks)
        np.save(d / "market.npy", market)

    kw = dict(lookback_window=20, target_window=10, stride=30)
    dm_nat = FinancialWindowDataModule(tmp_path / "nat", engine="native", **kw)
    dm_py = FinancialWindowDataModule(tmp_path / "py", engine="python", **kw)
    for dm in (dm_nat, dm_py):
        dm.prepare_data(verbose=False)
        dm.setup()

    nat, py = dm_nat.train_arrays(), dm_py.train_arrays()
    np.testing.assert_array_equal(nat.x, py.x)
    np.testing.assert_allclose(nat.y, py.y, atol=2e-5)
    np.testing.assert_allclose(nat.factor, py.factor, rtol=2e-5)
    np.testing.assert_allclose(nat.inv_psi, py.inv_psi, rtol=2e-3)
