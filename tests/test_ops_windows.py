"""Unit tests for window splitting and feature construction."""

import jax.numpy as jnp
import numpy as np
import pytest

from masters_thesis_tpu.ops import (
    lookback_target_split,
    add_quadratic_features,
    ols_features,
)


def _series(n_stocks=4, n_samples=200, seed=0):
    rng = np.random.default_rng(seed)
    r_stocks = rng.normal(size=(n_stocks, n_samples)).astype(np.float32)
    r_market = rng.normal(size=n_samples).astype(np.float32)
    return jnp.asarray(r_stocks), jnp.asarray(r_market)


def test_split_shapes_prediction():
    r_stocks, r_market = _series()
    x, y = lookback_target_split(r_stocks, r_market, 60, 30, stride=90)
    n_win = (200 - 90) // 90 + 1
    assert x.shape == (n_win, 4, 60, 2)
    assert y.shape == (n_win, 4, 30, 2)


def test_split_default_stride_is_nonoverlapping():
    r_stocks, r_market = _series(n_samples=300)
    x, y = lookback_target_split(r_stocks, r_market, 60, 40)
    assert x.shape[0] == 300 // 100


def test_split_window_contents_match_manual_slices():
    r_stocks, r_market = _series(n_stocks=2, n_samples=250)
    lookback, target, stride = 10, 5, 7
    x, y = lookback_target_split(r_stocks, r_market, lookback, target, stride)
    for w in range(x.shape[0]):
        start = w * stride
        np.testing.assert_array_equal(
            np.asarray(x[w, :, :, 0]), np.asarray(r_stocks[:, start : start + lookback])
        )
        np.testing.assert_array_equal(
            np.asarray(x[w, 0, :, 1]), np.asarray(r_market[start : start + lookback])
        )
        np.testing.assert_array_equal(
            np.asarray(y[w, :, :, 0]),
            np.asarray(r_stocks[:, start + lookback : start + lookback + target]),
        )


def test_split_reconstruction_mode_overlaps():
    r_stocks, r_market = _series(n_samples=100)
    x, y = lookback_target_split(
        r_stocks, r_market, 20, 8, stride=20, prediction=False
    )
    assert x.shape[2] == 20
    assert y.shape[2] == 8
    # Target is the tail of the lookback itself.
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x[:, :, 12:, :]))


def test_quadratic_features_interaction_only():
    r_stocks, r_market = _series()
    x, _ = lookback_target_split(r_stocks, r_market, 10, 5, stride=15)
    feats = add_quadratic_features(x, interaction_only=True)
    assert feats.shape[-1] == 3
    np.testing.assert_allclose(
        np.asarray(feats[..., 2]),
        np.asarray(x[..., 0] * x[..., 1]),
        rtol=1e-6,
    )


def test_quadratic_features_full_and_bias():
    r_stocks, r_market = _series()
    x, _ = lookback_target_split(r_stocks, r_market, 10, 5, stride=15)
    feats = add_quadratic_features(x, interaction_only=False, include_bias=True)
    assert feats.shape[-1] == 6
    np.testing.assert_allclose(np.asarray(feats[..., 3]), np.asarray(x[..., 0] ** 2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(feats[..., 5]), 1.0)


def test_ols_features_recovers_planted_coefficients():
    # Plant exact alpha/beta with tiny noise; ols_features must recover them.
    rng = np.random.default_rng(3)
    n_win, n_stocks, tw = 6, 5, 40
    alphas = rng.normal(size=(n_win, n_stocks)).astype(np.float32)
    betas = rng.normal(loc=1.0, size=(n_win, n_stocks)).astype(np.float32)
    r_market = rng.normal(size=(n_win, tw)).astype(np.float32)
    noise = 1e-3 * rng.normal(size=(n_win, n_stocks, tw)).astype(np.float32)
    r_stocks = alphas[..., None] + betas[..., None] * r_market[:, None, :] + noise

    target = jnp.stack(
        [jnp.asarray(r_stocks), jnp.broadcast_to(r_market[:, None, :], r_stocks.shape)],
        axis=-1,
    )
    a_hat, b_hat, factor, inv_psi = ols_features(target)
    np.testing.assert_allclose(np.asarray(a_hat), alphas, atol=5e-3)
    np.testing.assert_allclose(np.asarray(b_hat), betas, atol=5e-3)
    np.testing.assert_allclose(
        np.asarray(factor[:, 0]), r_market.mean(axis=-1), atol=1e-5
    )
    # Unbiased variance (ddof=1), matching torch's default.
    np.testing.assert_allclose(
        np.asarray(factor[:, 1]), r_market.var(axis=-1, ddof=1), rtol=1e-4
    )
    assert np.all(np.asarray(inv_psi) > 0)


def test_ols_features_inv_psi_is_inverse_residual_variance():
    rng = np.random.default_rng(4)
    n_win, n_stocks, tw = 3, 4, 25
    r_stocks = rng.normal(size=(n_win, n_stocks, tw)).astype(np.float32)
    r_market = rng.normal(size=(n_win, tw)).astype(np.float32)
    target = jnp.stack(
        [jnp.asarray(r_stocks), jnp.broadcast_to(r_market[:, None, :], r_stocks.shape)],
        axis=-1,
    )
    a_hat, b_hat, _, inv_psi = ols_features(target)
    a, b = np.asarray(a_hat), np.asarray(b_hat)
    resid = r_stocks - (a[..., None] + b[..., None] * r_market[:, None, :])
    np.testing.assert_allclose(
        np.asarray(inv_psi), 1.0 / resid.var(axis=-1, ddof=1), rtol=1e-3
    )


def test_split_reconstruction_rejects_target_longer_than_lookback():
    r_stocks, r_market = _series(n_samples=50)
    x, y = lookback_target_split(r_stocks, r_market, 10, 10, stride=10, prediction=False)
    assert y.shape[2] == 10
    with pytest.raises(ValueError, match="reconstruction"):
        lookback_target_split(r_stocks, r_market, 10, 15, stride=10, prediction=False)


def test_split_rejects_series_shorter_than_window():
    r_stocks, r_market = _series(n_samples=80)
    with pytest.raises(ValueError, match="shorter than one window"):
        lookback_target_split(r_stocks, r_market, 60, 30)
