"""Objective-function tests: parity relationships, batching semantics,
registry surface."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from masters_thesis_tpu.models import (
    LstmEncoder,
    ModelSpec,
    batched_objective,
    get_model_spec,
    make_combined_window,
    mse_window,
    nll_window,
)


def _window(k=6, t=10, seed=0):
    rng = np.random.default_rng(seed)
    alpha = jnp.asarray(rng.normal(size=(k, 1)), jnp.float32)
    beta = jnp.asarray(rng.normal(loc=1.0, size=(k, 1)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(k, t, 4)), jnp.float32)
    factor = jnp.asarray([0.05, 0.3], jnp.float32)
    inv_psi = jnp.asarray(rng.uniform(0.5, 2.0, size=k), jnp.float32)
    return alpha, beta, y, factor, inv_psi


def test_mse_window_matches_manual():
    alpha, beta, y, factor, inv_psi = _window()
    loss, metrics = mse_window(alpha, beta, y, factor, inv_psi)
    pred = np.asarray(alpha) + np.asarray(beta) * np.asarray(y[:, :, 1])
    expected = ((pred - np.asarray(y[:, :, 0])) ** 2).mean()
    np.testing.assert_allclose(float(loss), expected, rtol=1e-5)
    s, n = metrics["mse"]
    np.testing.assert_allclose(float(s) / float(n), expected, rtol=1e-5)


def test_nll_window_is_finite_and_penalizes_bad_mean():
    alpha, beta, y, factor, inv_psi = _window()
    loss, metrics = nll_window(alpha, beta, y, factor, inv_psi)
    assert np.isfinite(float(loss))
    worse, _ = nll_window(alpha + 10.0, beta, y, factor, inv_psi)
    assert float(worse) > float(loss)


def test_combined_is_weighted_sum():
    alpha, beta, y, factor, inv_psi = _window()
    mse, _ = mse_window(alpha, beta, y, factor, inv_psi)
    nll, _ = nll_window(alpha, beta, y, factor, inv_psi)
    for w in (0.0, 1.0, 100.0):
        comb, metrics = make_combined_window(w)(alpha, beta, y, factor, inv_psi)
        np.testing.assert_allclose(float(comb), float(nll) + w * float(mse), rtol=1e-5)
        assert set(metrics) == {"mse", "nll"}


def test_batched_objective_means_over_windows():
    b = 5
    windows = [_window(seed=i) for i in range(b)]
    batch = [jnp.stack([w[j] for w in windows]) for j in range(5)]
    loss, metrics = batched_objective(nll_window)(*batch)
    per_window = [float(nll_window(*w)[0]) for w in windows]
    np.testing.assert_allclose(float(loss), np.mean(per_window), rtol=1e-5)
    s, n = metrics["nll"]
    np.testing.assert_allclose(float(s), np.sum(per_window), rtol=1e-5)
    assert float(n) == b


def test_batched_mse_equals_flattened_mse():
    """Mean-of-per-window MSE == MSE over the flattened batch (the
    reference's flatten(0,1) formulation, src/model.py:193) when windows are
    equal-sized."""
    b = 4
    windows = [_window(seed=10 + i) for i in range(b)]
    batch = [jnp.stack([w[j] for w in windows]) for j in range(5)]
    loss, _ = batched_objective(mse_window)(*batch)
    alpha, beta, y = np.asarray(batch[0]), np.asarray(batch[1]), np.asarray(batch[2])
    pred = alpha + beta * y[:, :, :, 1]
    flat = ((pred - y[:, :, :, 0]) ** 2).mean()
    np.testing.assert_allclose(float(loss), flat, rtol=1e-5)


@pytest.mark.slow
def test_objective_differentiable_through_model():
    spec = ModelSpec(objective="combined", hidden_size=8, num_layers=2)
    model = spec.build_module()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(3, 4, 12, 3)), jnp.float32)  # (B,K,T,F)
    y = jnp.asarray(rng.normal(size=(3, 4, 6, 4)), jnp.float32)
    factor = jnp.asarray(rng.normal(size=(3, 2)) ** 2 + 0.1, jnp.float32)
    inv_psi = jnp.asarray(rng.uniform(0.5, 2.0, size=(3, 4)), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x[0])
    objective = batched_objective(spec.window_objective())

    def loss_fn(p):
        alpha, beta = jax.vmap(lambda xi: model.apply(p, xi))(x)
        loss, _ = objective(alpha, beta, y, factor, inv_psi)
        return loss

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    assert all(
        np.all(np.isfinite(np.asarray(g)))
        for g in jax.tree_util.tree_leaves(grads)
    )


def test_registry_surface():
    spec = get_model_spec("FinancialLstmNll", hidden_size=32, num_layers=4)
    assert spec.objective == "nll"
    assert spec.hidden_size == 32
    with pytest.raises(ValueError, match="Unknown module class"):
        get_model_spec("FinancialLstmBogus")
    assert isinstance(spec.build_module(), LstmEncoder)
