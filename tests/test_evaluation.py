"""Evaluation tests: result collection schema and the thesis ΔL metrics.

ΔL semantics (reference: tex/diplomski_rad.tex:1077-1084): loss above the
OLS-fit-on-the-TARGET-window baseline. Because target-window OLS minimizes
the squared error on exactly the window the losses are evaluated on, every
other estimator's ΔL_MSE is non-negative by construction — the tests lean on
that invariant.
"""

import numpy as np
import pytest

from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule
from masters_thesis_tpu.data.synthetic import SyntheticLogReturns
from masters_thesis_tpu.evaluation import collect_test_results, delta_losses
from masters_thesis_tpu.models.objectives import ModelSpec


@pytest.fixture(scope="module")
def eval_setup(tmp_path_factory):
    data_dir = tmp_path_factory.mktemp("eval_data")
    r_stocks, r_market, alphas, betas = SyntheticLogReturns.generate(
        n_stocks=6, n_samples=3000, seed=3
    )
    np.save(data_dir / "stocks.npy", np.asarray(r_stocks))
    np.save(data_dir / "market.npy", np.asarray(r_market))
    np.save(data_dir / "alphas.npy", np.asarray(alphas))
    np.save(data_dir / "betas.npy", np.asarray(betas))
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=16, target_window=8, stride=24
    )
    dm.prepare_data(verbose=False)
    dm.setup()

    spec = ModelSpec(objective="mse", hidden_size=8, num_layers=1, dropout=0.0)
    import jax
    import jax.numpy as jnp

    module = spec.build_module()
    params = module.init(
        jax.random.key(0), jnp.zeros((1, dm.lookback_window, dm.n_features))
    )["params"]
    return spec, params, dm


def test_collect_results_schema(eval_setup):
    spec, params, dm = eval_setup
    results = collect_test_results(spec, params, dm)
    n = len(dm.test_range)
    assert results["alpha"]["model"].shape == (n, 6)
    assert results["beta"]["true"].shape == (n, 6)
    assert np.isfinite(results["recon_residuals"]["ols"]).all()


def test_delta_losses_invariants(eval_setup):
    spec, params, dm = eval_setup
    deltas = delta_losses(spec, params, dm)

    for key in ("model", "ols"):
        d = deltas[key]
        assert np.isfinite([d["delta_mse"], d["delta_nll"], d["delta_mix"]]).all()
        # Target-window OLS is the per-window MSE minimizer.
        assert d["delta_mse"] >= -1e-9
        assert d["delta_mix"] == pytest.approx(
            d["delta_nll"] + deltas["zeta"] * d["delta_mse"], rel=1e-6
        )
    assert np.isfinite(deltas["baseline"]["nll"])
    # An untrained encoder should sit above the analytical OLS estimator.
    assert deltas["model"]["delta_mse"] > deltas["ols"]["delta_mse"]


def test_delta_losses_reuses_collected_estimates(eval_setup):
    spec, params, dm = eval_setup
    results = collect_test_results(spec, params, dm)
    direct = delta_losses(spec, params, dm)
    reused = delta_losses(spec, params, dm, estimates=results)
    for key in ("model", "ols"):
        for metric in ("delta_mse", "delta_nll", "delta_mix"):
            assert reused[key][metric] == pytest.approx(
                direct[key][metric], rel=1e-5
            )
