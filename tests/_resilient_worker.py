"""Subprocess trainee for the resilience chaos suite.

Runs a real Trainer.fit on the 8-device virtual CPU mesh (same forced
platform as conftest.py — set BEFORE jax imports) with epoch-granular
checkpointing and auto-resume, then dumps the final params to
``<out>/params.npz``. The kill-resume determinism test launches this twice:
once uninterrupted (the reference params), once under the RunSupervisor
with an injected SIGKILL mid-epoch (the supervised attempt chain) — the
two npz files must be bit-identical.

Usage: python tests/_resilient_worker.py <out_dir> [max_epochs]
"""

import os
import sys
from pathlib import Path

# The package is run from the repo, not installed: python <this file> puts
# tests/ (not the repo root) on sys.path.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # beat the axon sitecustomize

import numpy as np  # noqa: E402


def main() -> int:
    out = Path(sys.argv[1])
    max_epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    out.mkdir(parents=True, exist_ok=True)

    from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule
    from masters_thesis_tpu.data.synthetic import SyntheticLogReturns
    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.telemetry import TelemetryRun
    from masters_thesis_tpu.train import Trainer

    data_dir = out / "data"
    if not (data_dir / "stocks.npy").exists():
        data_dir.mkdir(parents=True, exist_ok=True)
        r_stocks, r_market, _, _ = SyntheticLogReturns.generate(
            n_stocks=8, n_samples=4000, seed=1
        )
        np.save(data_dir / "stocks.npy", np.asarray(r_stocks))
        np.save(data_dir / "market.npy", np.asarray(r_market))
    dm = FinancialWindowDataModule(
        data_dir, lookback_window=16, target_window=8, stride=24, batch_size=2
    )
    dm.prepare_data(verbose=False)
    dm.setup()

    spec = ModelSpec(
        objective="mse",
        hidden_size=8,
        num_layers=1,
        dropout=0.0,
        learning_rate=1e-2,
    )
    telemetry = TelemetryRun(out / "telemetry")
    trainer = Trainer(
        max_epochs=max_epochs,
        gradient_clip_val=5.0,
        # Val every 2 epochs so the NEW cadence path (not the val-epoch
        # save) is what persists the odd epochs' progress.
        check_val_every_n_epoch=2,
        checkpoint_every_n_epochs=1,
        enable_progress_bar=False,
        enable_model_summary=False,
        seed=0,
        ckpt_dir=out / "ckpts",
        resume="auto",
        telemetry=telemetry,
    )
    result = trainer.fit(spec, dm)
    telemetry.close()

    leaves = jax.tree_util.tree_leaves(jax.device_get(result.params))
    np.savez(out / "params.npz", **{f"p{i}": a for i, a in enumerate(leaves)})
    return 0


if __name__ == "__main__":
    sys.exit(main())
